"""Datasource constructors for ray_tpu.data.

Reference: python/ray/data/read_api.py (range, from_items, read_parquet,
read_csv, read_json, read_binary_files, read_images). Each reader builds a
Dataset whose producers are zero-arg callables executed remotely — file IO
happens on cluster workers, one fused task per block.
"""

from __future__ import annotations

import builtins
import functools
import glob as glob_mod
import os
from typing import Any, List, Optional, Sequence, Union

import numpy as np

from ray_tpu.data.dataset import Dataset


def _chunk_bounds(n: int, k: int):
    # NB: module-level `range()` below shadows the builtin (API parity with
    # ray.data.range), hence builtins.range here
    return [((n * i) // k, (n * (i + 1)) // k) for i in builtins.range(k)]


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001 — API parity
    """Dataset of {"id": int64} rows 0..n-1 (reference: ray.data.range)."""
    k = parallelism if parallelism > 0 else min(max(1, n // 1000), 200)
    producers = [
        functools.partial(_range_block, lo, hi) for lo, hi in _chunk_bounds(n, k)
    ]
    return Dataset(producers)


def _range_block(lo: int, hi: int):
    return {"id": np.arange(lo, hi, dtype=np.int64)}


def from_items(items: Sequence[Any], *, parallelism: int = -1) -> Dataset:
    """Dataset from a local list (reference: ray.data.from_items)."""
    from ray_tpu.data.block import rows_to_block

    items = list(items)
    k = parallelism if parallelism > 0 else min(max(1, len(items) // 1000), 200)
    k = max(1, min(k, len(items) or 1))
    blocks = [
        rows_to_block(items[lo:hi]) for lo, hi in _chunk_bounds(len(items), k)
    ]
    return Dataset([functools.partial(_identity, b) for b in blocks])


def _identity(b):
    return b


def from_numpy(arr: np.ndarray, *, column: str = "data",
               parallelism: int = -1) -> Dataset:
    k = parallelism if parallelism > 0 else min(max(1, len(arr) // 100_000), 200)
    return Dataset([
        functools.partial(_identity, {column: arr[lo:hi]})
        for lo, hi in _chunk_bounds(len(arr), k)
    ])


def _expand_paths(paths: Union[str, Sequence[str]], suffixes=None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _, files in os.walk(p):
                out.extend(os.path.join(root, f) for f in sorted(files))
        elif any(c in p for c in "*?["):
            out.extend(sorted(glob_mod.glob(p)))
        else:
            out.append(p)
    if suffixes:
        out = [p for p in out if any(p.endswith(s) for s in suffixes)]
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


def read_parquet(paths: Union[str, Sequence[str]], *, columns=None) -> Dataset:
    """One block per parquet file, columnar numpy (reference: read_parquet)."""
    files = _expand_paths(paths, suffixes=[".parquet"])
    return Dataset([
        functools.partial(_read_parquet_file, f, columns) for f in files
    ])


def _read_parquet_file(path: str, columns):
    import pyarrow.parquet as pq

    table = pq.read_table(path, columns=columns)
    return {
        name: col.to_numpy(zero_copy_only=False)
        for name, col in zip(table.column_names, table.columns)
    }


def read_csv(paths: Union[str, Sequence[str]], **pandas_kwargs) -> Dataset:
    files = _expand_paths(paths, suffixes=[".csv"])
    return Dataset([
        functools.partial(_read_csv_file, f, pandas_kwargs) for f in files
    ])


def _read_csv_file(path: str, pandas_kwargs):
    import pandas as pd

    df = pd.read_csv(path, **pandas_kwargs)
    return {c: df[c].to_numpy() for c in df.columns}


def read_json(paths: Union[str, Sequence[str]], *, lines: bool = True) -> Dataset:
    files = _expand_paths(paths, suffixes=[".json", ".jsonl"])
    return Dataset([
        functools.partial(_read_json_file, f, lines) for f in files
    ])


def _read_json_file(path: str, lines: bool):
    import pandas as pd

    df = pd.read_json(path, lines=lines)
    return {c: df[c].to_numpy() for c in df.columns}


def read_binary_files(paths: Union[str, Sequence[str]],
                      *, include_paths: bool = False,
                      parallelism: int = -1) -> Dataset:
    files = _expand_paths(paths)
    k = parallelism if parallelism > 0 else min(len(files), 64)
    return Dataset([
        functools.partial(_read_binary_chunk, files[lo:hi], include_paths)
        for lo, hi in _chunk_bounds(len(files), k)
    ])


def _read_binary_chunk(files: List[str], include_paths: bool):
    rows = []
    for f in files:
        with open(f, "rb") as fh:
            data = fh.read()
        rows.append({"path": f, "bytes": data} if include_paths else {"bytes": data})
    return rows


def read_images(paths: Union[str, Sequence[str]], *, size=None,
                mode: str = "RGB", parallelism: int = -1) -> Dataset:
    """Decode images into {"image": uint8 HWC} rows; `size=(h, w)` resizes so
    blocks stack into one array (reference: ray.data.read_images)."""
    files = _expand_paths(
        paths, suffixes=[".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp"]
    )
    k = parallelism if parallelism > 0 else min(len(files), 64)
    return Dataset([
        functools.partial(_read_image_chunk, files[lo:hi], size, mode)
        for lo, hi in _chunk_bounds(len(files), k)
    ])


def _read_image_chunk(files: List[str], size, mode: str):
    from PIL import Image

    arrays = []
    for f in files:
        img = Image.open(f).convert(mode)
        if size is not None:
            img = img.resize((size[1], size[0]))
        arrays.append(np.asarray(img))
    if size is not None:
        return {"image": np.stack(arrays)}
    return [{"image": a} for a in arrays]


def _validate_sql_identifier(name: str) -> str:
    """Quote `partition_column` as a SQL identifier. Only plain identifiers
    (letters/digits/underscore, possibly dotted) are accepted — the column
    name is spliced into the query text, so anything else is rejected
    rather than passed through."""
    import re

    if not isinstance(name, str) or not re.fullmatch(
            r"[A-Za-z_][A-Za-z0-9_]*(\.[A-Za-z_][A-Za-z0-9_]*)?", name):
        raise ValueError(
            f"partition_column {name!r} is not a plain SQL identifier "
            "(letters, digits, underscores, optional single dot)")
    # standard SQL double-quoting; the dotted form quotes each part
    return ".".join('"%s"' % part for part in name.split("."))


def _validate_sql_bound(value, which: str) -> float:
    """Range bounds must be real numbers: they are spliced as numeric
    literals (paramstyle varies across DB-API drivers), and range
    partitioning itself is numeric-only."""
    import numbers

    if isinstance(value, bool) or not isinstance(value, numbers.Real):
        raise TypeError(
            f"read_sql {which} must be a real number for numeric range "
            f"partitioning, got {type(value).__name__}: {value!r}. "
            "String/timestamp/date partition columns are not supported — "
            "partition on a numeric key (e.g. an integer id) instead.")
    return float(value)


def read_sql(sql: str, connection_factory, *, parallelism: int = 1,
             partition_column: Optional[str] = None,
             lower_bound=None, upper_bound=None) -> Dataset:
    """Read a SQL query through a DB-API connection factory (reference:
    python/ray/data/read_api.py read_sql / datasource/sql_datasource.py).

    `connection_factory` is a zero-arg callable returning a DB-API 2.0
    connection (sqlite3.connect(...), psycopg2.connect(...), ...) — it runs
    INSIDE the read tasks, so the connection never pickles. With
    `partition_column` + bounds, `parallelism` tasks each read one range
    slice of the query (the standard JDBC-style range split); otherwise one
    task reads the whole result.

    Range partitioning is NUMERIC-ONLY: `partition_column` must hold real
    numbers and `lower_bound`/`upper_bound` must be numbers (they become
    numeric literals in the generated predicates). The column name must be
    a plain identifier; it is validated and quoted before being spliced
    into the query."""
    if parallelism > 1 and partition_column is None:
        raise ValueError("parallel read_sql needs partition_column + bounds")
    if partition_column is not None:
        partition_column = _validate_sql_identifier(partition_column)

    def _read_range(lo, hi):
        conn = connection_factory()
        try:
            cur = conn.cursor()
            if lo is None and hi is None:
                cur.execute(sql)
            else:
                # numeric literals, not driver placeholders: paramstyle
                # varies across DB-API drivers (sqlite qmark, psycopg2
                # pyformat, ...) and the bounds are framework-generated
                # numbers, never user strings
                preds = []
                if lo is not None:
                    preds.append(f"{partition_column} >= {float(lo)!r}")
                if hi is not None:
                    preds.append(f"{partition_column} < {float(hi)!r}")
                cur.execute(
                    f"SELECT * FROM ({sql}) AS _rt_sub "
                    f"WHERE {' AND '.join(preds)}")
            cols = [d[0] for d in cur.description]
            rows = cur.fetchall()
        finally:
            conn.close()
        import numpy as np

        return {c: np.asarray([r[i] for r in rows])
                for i, c in enumerate(cols)}

    if partition_column is None or parallelism <= 1:
        return Dataset([functools.partial(_read_range, None, None)])
    if lower_bound is None or upper_bound is None:
        raise ValueError("parallel read_sql needs lower_bound/upper_bound")
    lower_bound = _validate_sql_bound(lower_bound, "lower_bound")
    upper_bound = _validate_sql_bound(upper_bound, "upper_bound")
    if upper_bound < lower_bound:
        raise ValueError(
            f"read_sql upper_bound ({upper_bound}) must be >= lower_bound "
            f"({lower_bound})")
    span = (float(upper_bound) - float(lower_bound)) / parallelism
    producers = []
    for i in builtins.range(parallelism):
        # JDBC-style split: bounds set the STRIDE; the edge partitions are
        # unbounded so rows outside [lower_bound, upper_bound) still land
        # somewhere instead of silently vanishing
        lo = None if i == 0 else lower_bound + span * i
        hi = (None if i == parallelism - 1
              else lower_bound + span * (i + 1))
        producers.append(functools.partial(_read_range, lo, hi))
    return Dataset(producers)
