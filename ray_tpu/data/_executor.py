"""Streaming executor v2: per-stage dispatch, byte-budget backpressure,
actor-pool autoscaling, and per-op stats.

Reference surface: python/ray/data/_internal/execution/streaming_executor.py
:106,423,499 (dedicated scheduling loop), resource_manager.py (in-flight
byte budgets per operator), operators/actor_pool_map_operator.py (min/max
actor autoscaling), python/ray/data/stats.py (per-op timing surfaced by
ds.stats()).

Redesign: the driver runs one pull-based scheduling loop per consumption.
Each stage owns an input queue of block refs and a set of in-flight tasks;
a completed task's output ref moves to the next stage's queue. Admission is
gated by (a) a per-stage in-flight BYTE budget — block sizes are measured
from the node's shm store, falling back to a running average for inline
objects — and (b) the consumer's pull (the bounded, in-order output
buffer). Stateful stages run through an auto-scaling actor pool: the pool
grows while its input queue is deeper than its actors can cover and shrinks
back to min when the queue drains.
"""

from __future__ import annotations

import collections
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

from ray_tpu.data.block import Block, normalize_batch

_SMALL_OBJECT_EST = 64 * 1024  # inline objects: assume 64KB until measured
_exec_counter = __import__("itertools").count(1)


# ---------------------------------------------------------------------------
# stats
# ---------------------------------------------------------------------------


@dataclass
class OpStats:
    """One pipeline stage's execution metrics (reference: data/stats.py)."""

    name: str
    blocks: int = 0
    bytes_out: int = 0
    task_s_total: float = 0.0       # submit→complete, summed over blocks
    task_s_max: float = 0.0
    peak_in_flight: int = 0
    peak_queued: int = 0
    actors_peak: int = 0            # actor stages only
    backpressure_s: float = 0.0     # time admission was byte-blocked

    def row(self) -> str:
        avg = self.task_s_total / self.blocks if self.blocks else 0.0
        return (f"{self.name[:34]:34} {self.blocks:>6} "
                f"{self.bytes_out / 1e6:>9.1f} {avg * 1e3:>9.1f} "
                f"{self.task_s_max * 1e3:>9.1f} {self.peak_in_flight:>5} "
                f"{self.peak_queued:>5} {self.backpressure_s:>7.2f}")


@dataclass
class DatasetStats:
    """Per-op table + totals; str() renders the table the way the
    reference's ds.stats() does."""

    ops: List[OpStats] = field(default_factory=list)
    wall_s: float = 0.0
    output_blocks: int = 0
    output_bytes: int = 0

    def __str__(self) -> str:
        hdr = (f"{'op':34} {'blocks':>6} {'MB out':>9} {'avg ms':>9} "
               f"{'max ms':>9} {'infl':>5} {'queue':>5} {'bp s':>7}")
        lines = [hdr, "-" * len(hdr)]
        lines += [o.row() for o in self.ops]
        lines.append(
            f"total: {self.output_blocks} blocks, "
            f"{self.output_bytes / 1e6:.1f} MB out, "
            f"wall {self.wall_s:.2f}s")
        return "\n".join(lines)

    def to_dict(self) -> dict:
        return {
            "wall_s": round(self.wall_s, 4),
            "output_blocks": self.output_blocks,
            "output_bytes": self.output_bytes,
            "ops": [vars(o) for o in self.ops],
        }


_STATS_REGISTRY: "collections.OrderedDict[str, DatasetStats]" = (
    collections.OrderedDict())

def _rt_metrics_emit(stats: DatasetStats) -> None:
    """Thread per-execution totals onto the rt_* metrics plane (the
    cluster-wide Prometheus surface — reference: data's StatsManager
    pushing operator metrics through the metrics agent)."""
    try:
        from ray_tpu.util.metrics import get_or_create_counter

        get_or_create_counter(
            "rt_data_executions_total", "Dataset plan executions").inc(1)
        if stats.output_blocks:
            get_or_create_counter(
                "rt_data_output_blocks_total",
                "Dataset output blocks").inc(stats.output_blocks)
        if stats.output_bytes:
            get_or_create_counter(
                "rt_data_output_bytes_total",
                "Dataset output bytes").inc(stats.output_bytes)
        for op in stats.ops:
            if op.blocks:
                get_or_create_counter(
                    "rt_data_op_blocks_total",
                    "Blocks processed per logical op",
                    tag_keys=("op",)).inc(op.blocks,
                                          tags={"op": op.name[:60]})
    except Exception:  # noqa: BLE001 — metrics must never fail the pipeline
        pass


def record_stats(dataset_tag: str, stats: DatasetStats, *,
                 emit_metrics: bool = True) -> None:
    _STATS_REGISTRY[dataset_tag] = stats
    if emit_metrics:
        # metadata-shortcut queries pass False: they count under
        # rt_data_meta_shortcuts_total, not as plan executions
        _rt_metrics_emit(stats)
    while len(_STATS_REGISTRY) > 64:
        _STATS_REGISTRY.popitem(last=False)
    # surface through the control store so the state API can list dataset
    # executions cluster-wide (reference: data dashboard / StatsManager)
    try:
        import json

        from ray_tpu._private.core_worker import get_core_worker

        cw = get_core_worker()
        cw.run_sync(cw.control.call("kv_put", {
            "ns": "data_stats", "key": dataset_tag.encode(),
            "value": json.dumps(stats.to_dict()).encode(),
            "overwrite": True,
        }))
    except Exception:  # noqa: BLE001 — stats must never fail the pipeline
        pass


def list_recorded_stats() -> Dict[str, DatasetStats]:
    return dict(_STATS_REGISTRY)


# ---------------------------------------------------------------------------
# sizing
# ---------------------------------------------------------------------------


def _ref_size(ref) -> Optional[int]:
    """Size of a block ref if it lives in the local shm store (zero-copy
    metadata peek), else None (inline/memory-store object)."""
    try:
        from ray_tpu._private.core_worker import get_core_worker

        store = get_core_worker().store
        if store is None:
            return None
        got = store.get(ref._id)
        if got is None:
            return None
        view, _ = got
        size = len(view)
        view.release()
        store.release(ref._id)
        return size
    except Exception:  # noqa: BLE001 — sizing is best-effort
        return None


# ---------------------------------------------------------------------------
# auto-scaling actor pool
# ---------------------------------------------------------------------------


_MAP_WORKER_CLS = None


def _map_worker_cls():
    """The one remote map-worker wrapper, shared by every pool (streaming
    and materialize paths must behave identically)."""
    global _MAP_WORKER_CLS
    if _MAP_WORKER_CLS is None:
        import ray_tpu

        @ray_tpu.remote
        class _MapWorker:
            def __init__(self, cls, args, kwargs):
                self._fn = cls(*args, **kwargs)

            def transform(self, block):
                return self._fn(normalize_batch(block))

        _MAP_WORKER_CLS = _MapWorker
    return _MAP_WORKER_CLS


class AutoScalingActorPool:
    """Least-loaded actor pool with queue-driven scaling (reference:
    actor_pool_map_operator.py + actor_autoscaler)."""

    def __init__(self, udf_cls, fn_args, fn_kwargs, min_size: int,
                 max_size: int):
        self._worker_cls = _map_worker_cls()
        self._ctor = (udf_cls, list(fn_args), dict(fn_kwargs))
        self.min_size = max(1, min_size)
        self.max_size = max(self.min_size, max_size)
        self._actors: List[Any] = []
        self._load: Dict[int, int] = {}  # actor index -> outstanding
        self._by_ref: Dict[bytes, int] = {}  # result ref -> actor index
        for _ in range(self.min_size):
            self._add_actor()
        self._idle_polls = 0

    def _add_actor(self):
        self._actors.append(self._worker_cls.remote(*self._ctor))
        self._load[len(self._actors) - 1] = 0

    def submit(self, block_ref):
        i = min(self._load, key=self._load.get)
        self._load[i] += 1
        ref = self._actors[i].transform.remote(block_ref)
        self._by_ref[ref._id.binary()] = i
        return ref

    def task_done(self, ref):
        i = self._by_ref.pop(ref._id.binary(), None)
        if i is not None and i in self._load:
            self._load[i] = max(0, self._load[i] - 1)

    def autoscale(self, queued: int) -> None:
        """Grow while the queue is deeper than the pool can cover; shrink
        back toward min after sustained idleness."""
        size = len(self._actors)
        if queued > size and size < self.max_size:
            self._add_actor()
            self._idle_polls = 0
            return
        if queued == 0 and all(v == 0 for v in self._load.values()):
            self._idle_polls += 1
            if self._idle_polls >= 20 and size > self.min_size:
                import ray_tpu

                idx = size - 1
                try:
                    ray_tpu.kill(self._actors[idx])
                except Exception:  # noqa: BLE001 — already dead
                    pass
                self._actors.pop()
                self._load.pop(idx, None)
                self._idle_polls = 0
        else:
            self._idle_polls = 0

    @property
    def size(self) -> int:
        return len(self._actors)

    def shutdown(self):
        import ray_tpu

        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001 — already dead
                pass
        # drop load bookkeeping for submissions whose task_done never came
        # (the materialize path is fire-and-forget — see _Pipeline)
        self._by_ref.clear()
        self._load = {i: 0 for i in self._load}


# ---------------------------------------------------------------------------
# the executor
# ---------------------------------------------------------------------------


def _actor_label(cls) -> str:
    return getattr(cls, "__name__", None) or getattr(
        getattr(cls, "func", None), "__name__", "udf")


def _stage_name(stage) -> str:
    if stage[0] == "tasks":
        ops = stage[1]
        return "->".join(k for k, _ in ops) if ops else "read"
    return f"actors[{_actor_label(stage[1])}]"


class _StageState:
    def __init__(self, stage, idx: int, pool: Optional[AutoScalingActorPool]):
        self.stage = stage
        self.idx = idx
        self.pool = pool
        self.queue: "collections.deque" = collections.deque()
        self.in_flight: Dict[bytes, Any] = {}   # ref id -> (ref, t0, order, est)
        self.bytes_in_flight = 0
        self.stats = OpStats(name=_stage_name(stage))
        self.avg_size = float(_SMALL_OBJECT_EST)
        self._bp_since: Optional[float] = None
        self.named_run = None  # segment-named RemoteFunction, built lazily


class StreamingExecutorV2:
    """Pull-driven scheduling loop with byte budgets (see module doc)."""

    def __init__(self, producers, stages, *, window: int,
                 max_bytes_per_op: Optional[int] = None, tag: str = ""):
        from ray_tpu.data.context import DataContext

        ctx = DataContext.get_current()
        self.window = max(1, window)
        self.max_bytes = max_bytes_per_op or ctx.op_memory_budget_bytes
        self.tag = tag or f"ds-{next(_exec_counter)}"
        self.producers = list(producers)
        from ray_tpu.remote_function import RemoteFunction

        from ray_tpu.data.dataset import _run_chain

        self._run = RemoteFunction(_run_chain)
        stages = list(stages)
        if stages and stages[0][0] == "actors":
            # actor stages take materialized BLOCKS; a callable source
            # materializes through one producer task first
            stages.insert(0, ("tasks", []))
        self.stages: List[_StageState] = []
        for i, st in enumerate(stages):
            pool = None
            if st[0] == "actors":
                _, cls, args, kwargs, conc = st
                lo, hi = conc if isinstance(conc, tuple) else (conc, conc)
                pool = AutoScalingActorPool(cls, args, kwargs, lo, hi)
            self.stages.append(_StageState(st, i, pool))

    # -- submission helpers ---------------------------------------------

    def _submit(self, ss: _StageState, item, order: int):
        if ss.stage[0] == "tasks":
            run = ss.named_run
            if run is None:
                # one span per operator-segment task: the task NAME carries
                # the segment's op chain, so its execution span (and the
                # state API / timeline rows) read "data:read->map" instead
                # of "_run_chain" — built lazily, cached per stage
                run = ss.named_run = self._run.options(
                    name=f"data:{ss.stats.name[:48]}")
            ref = run.remote(item, ss.stage[1])
        else:
            ref = ss.pool.submit(item)
        ss.in_flight[ref._id.binary()] = (ref, time.perf_counter(), order,
                                          ss.avg_size)
        ss.bytes_in_flight += ss.avg_size
        ss.stats.peak_in_flight = max(ss.stats.peak_in_flight,
                                      len(ss.in_flight))
        return ref

    def _harvest(self, timeout: float):
        """Move completed tasks' outputs downstream; returns finals list of
        (order, ref) that completed the LAST stage."""
        import ray_tpu

        all_refs = [v[0] for ss in self.stages for v in ss.in_flight.values()]
        finals = []
        if not all_refs:
            return finals
        ready, _ = ray_tpu.wait(all_refs,
                                num_returns=len(all_refs), timeout=timeout)
        if not ready:
            return finals
        ready_ids = {r._id.binary() for r in ready}
        for ss in self.stages:
            done = [k for k in ss.in_flight if k in ready_ids]
            for k in done:
                ref, t0, order, est = ss.in_flight.pop(k)
                ss.bytes_in_flight -= est
                dt = time.perf_counter() - t0
                ss.stats.blocks += 1
                ss.stats.task_s_total += dt
                ss.stats.task_s_max = max(ss.stats.task_s_max, dt)
                size = _ref_size(ref)
                if size is not None:
                    # EMA of observed output size feeds the byte budget
                    ss.avg_size = 0.7 * ss.avg_size + 0.3 * size
                    ss.stats.bytes_out += size
                else:
                    ss.stats.bytes_out += int(ss.avg_size)
                if ss.pool is not None:
                    ss.pool.task_done(ref)
                nxt = ss.idx + 1
                if nxt < len(self.stages):
                    self.stages[nxt].queue.append((order, ref))
                    self.stages[nxt].stats.peak_queued = max(
                        self.stages[nxt].stats.peak_queued,
                        len(self.stages[nxt].queue))
                else:
                    finals.append((order, ref))
        return finals

    def _admit(self):
        """Admit queued blocks into each stage under the byte budget."""
        now = time.perf_counter()
        for ss in self.stages:
            cap_blocks = self.window if ss.pool is None else max(
                self.window, 2 * ss.pool.size)
            blocked = False
            while ss.queue:
                # always admit ONE block when nothing is in flight — a block
                # larger than the budget must throttle to serial execution,
                # not deadlock the stage
                if ss.in_flight and (
                        len(ss.in_flight) >= cap_blocks
                        or ss.bytes_in_flight + ss.avg_size > self.max_bytes):
                    blocked = True
                    break
                order, item = ss.queue.popleft()
                self._submit(ss, item, order)
            if blocked:
                if ss._bp_since is None:
                    ss._bp_since = now
            elif ss._bp_since is not None:
                ss.stats.backpressure_s += now - ss._bp_since
                ss._bp_since = None
            if ss.pool is not None:
                ss.pool.autoscale(len(ss.queue) + len(ss.in_flight))
                ss.stats.actors_peak = max(ss.stats.actors_peak, ss.pool.size)

    # -- the loop --------------------------------------------------------

    def run(self) -> Iterator[Block]:
        import ray_tpu

        from ray_tpu.util import tracing

        # driver-side execution span: every segment task submitted by this
        # loop chains under it, so one dataset consumption reads as one
        # trace in timeline(). The contextvar is installed only around the
        # submit/harvest region of each scheduling turn — never across a
        # yield, where it would leak into (and mis-parent) whatever else
        # the consumer does between blocks
        exec_sp = tracing.start_manual_span(f"data:execute:{self.tag}")
        t_start = time.perf_counter()
        stats = DatasetStats()
        first = self.stages[0]
        src = collections.deque(enumerate(self.producers))
        out_buf: Dict[int, Any] = {}
        next_out = 0
        emitted = 0
        total = len(self.producers)
        try:
            while emitted < total:
                with tracing.installed_span(exec_sp):
                    # source admission rides the same budget as every stage
                    # and is additionally gated on delivery progress so a
                    # straggler at a low order can't pile finished blocks
                    # into out_buf (constant-footprint contract); an empty
                    # stage always admits one block even over budget
                    while src and src[0][0] - next_out < 2 * self.window and (
                            not first.in_flight
                            or (len(first.in_flight) < self.window
                                and first.bytes_in_flight + first.avg_size
                                <= self.max_bytes)):
                        order, producer = src.popleft()
                        self._submit(first, producer, order)
                    for order, ref in self._harvest(timeout=0.05):
                        out_buf[order] = ref
                    self._admit()
                # in-order delivery; the pull is the final backpressure
                while next_out in out_buf:
                    ref = out_buf.pop(next_out)
                    block = ray_tpu.get(ref, timeout=600)
                    size = _ref_size(ref)
                    stats.output_bytes += (
                        size if size is not None else _SMALL_OBJECT_EST)
                    stats.output_blocks += 1
                    del ref
                    next_out += 1
                    emitted += 1
                    yield block
        finally:
            for ss in self.stages:
                if ss._bp_since is not None:
                    ss.stats.backpressure_s += (
                        time.perf_counter() - ss._bp_since)
                if ss.pool is not None:
                    ss.pool.shutdown()
            stats.ops = [ss.stats for ss in self.stages]
            stats.wall_s = time.perf_counter() - t_start
            record_stats(self.tag, stats)
            self.last_stats = stats
            tracing.end_manual_span(exec_sp, blocks=stats.output_blocks)

    def __iter__(self) -> Iterator[Block]:
        return self.run()
