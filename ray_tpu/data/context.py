"""Execution context for ray_tpu.data (reference:
python/ray/data/context.py DataContext — the knobs the streaming executor
and resource manager read)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass
class DataContext:
    """Per-driver data-execution settings.

    streaming_block_window — max source blocks in flight end-to-end during
    streaming consumption (iter_batches / iter_rows / take on an
    unmaterialized dataset). The memory ceiling is roughly
    window × max block size; consumed blocks free their shm copies before
    new ones are admitted (reference: streaming_executor resource manager's
    bounded operator memory).
    """

    streaming_block_window: int = 8
    # the logical optimizer escape hatch: False compiles the plan naively
    # (one stage per op, no pushdowns, no metadata shortcuts — limit
    # SEMANTICS still hold, they are compilation, not optimization).
    # bench_data.py A/Bs this flag.
    optimizer_enabled: bool = True
    # max estimated bytes in flight per pipeline stage before admission
    # backpressure (reference: execution/resource_manager.py budgets)
    op_memory_budget_bytes: int = 128 << 20
    # shuffle-class ops: target partition size + fan-out cap (B blocks x
    # B partitions return-ref blowup guard)
    shuffle_target_partition_bytes: int = 64 << 20
    shuffle_max_partitions: int = 64
    # advisory target for readers choosing block splits
    target_max_block_size: int = 128 * 1024 * 1024

    _current: "Optional[DataContext]" = None

    @classmethod
    def get_current(cls) -> "DataContext":
        if cls._current is None:
            cls._current = DataContext()
        return cls._current
