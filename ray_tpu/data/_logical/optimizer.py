"""Fixpoint driver for the logical rules.

Reference: python/ray/data/_internal/logical/optimizers.py
(LogicalOptimizer.optimize — apply each rule until the plan stops
changing). Every firing is recorded (and counted on the rt_* metrics
plane) so `explain()` can show which rules shaped the plan.
"""

from __future__ import annotations

from typing import List, Tuple

from ray_tpu.data._logical import operators as ops
from ray_tpu.data._logical import rules as rules_mod

_MAX_PASSES = 20

def _count_rule(rule_name: str, n: int) -> None:
    try:
        from ray_tpu.util.metrics import get_or_create_counter

        get_or_create_counter(
            "rt_data_rules_fired_total",
            "Logical-optimizer rule firings",
            tag_keys=("rule",)).inc(n, tags={"rule": rule_name})
    except Exception:  # noqa: BLE001 — metrics must never fail planning
        pass


def _fixpoint(root: ops.LogicalOp, rule_classes,
              fired: List[str]) -> ops.LogicalOp:
    for _ in range(_MAX_PASSES):
        changed = False
        for cls in rule_classes:
            root, hits = cls().apply(root)
            if hits:
                changed = True
                fired.extend(hits)
                _count_rule(cls.name, len(hits))
        if not changed:
            break
    return root


def optimize(root: ops.LogicalOp) -> Tuple[ops.LogicalOp, List[str]]:
    """Run rewrite rules to fixpoint, then fusion to fixpoint. Returns
    (optimized_root, fired) — fired is the ordered rule-firing log."""
    fired: List[str] = []
    root = _fixpoint(root, rules_mod.REWRITE_RULES, fired)
    root = _fixpoint(root, rules_mod.FUSION_RULES, fired)
    return root, fired
