"""Logical operators for the ray_tpu.data query planner.

Reference surface: python/ray/data/_internal/logical/operators/ (Read,
AbstractMap, Limit, Project, AllToAll ops, Union/Zip/Join) — the node
vocabulary the rule-based optimizer rewrites and the physical planner
compiles (planner.py here; `_internal/planner/planner.py:230` there).

A Dataset holds exactly one of these trees and never mutates it: every
transform stacks a node. Nodes are cheap immutable-ish records; rules
rebuild subtrees via `with_inputs` (shallow copy, so the execution caches
on materializing nodes are shared between the pre- and post-rewrite
plans).
"""

from __future__ import annotations

import copy
from typing import Any, Callable, List, Optional, Sequence, Tuple

# one fused op: (kind, payload). kind in {"map", "map_batches", "filter",
# "flat_map", "project", "filter_expr", "limit"}; payload is the UDF — or
# the column list / predicate list / row cap for the data-driven kinds.
FusedOp = Tuple[str, Any]

_EXPR_OPS = ("==", "=", "!=", "<", "<=", ">", ">=", "in", "not in")


def normalize_filter_expr(expr) -> List[tuple]:
    """Validate a structured predicate: one (col, op, value) tuple or a
    list of them (AND semantics — the pyarrow `filters=` DNF conjunction
    shape, which is exactly what predicate pushdown hands the parquet
    reader)."""
    exprs = [expr] if isinstance(expr, tuple) else list(expr)
    out = []
    for e in exprs:
        if (not isinstance(e, tuple) or len(e) != 3
                or not isinstance(e[0], str) or e[1] not in _EXPR_OPS):
            raise ValueError(
                f"filter expr must be (column, op, value) with op in "
                f"{_EXPR_OPS}, got {e!r}")
        out.append((e[0], "==" if e[1] == "=" else e[1], e[2]))
    return out


def expr_columns(exprs: List[tuple]) -> List[str]:
    return sorted({c for c, _op, _v in exprs})


class LogicalOp:
    """Base logical node. `inputs` are upstream nodes (dataflow order:
    inputs produce the rows this node consumes)."""

    name = "Op"

    def __init__(self, *inputs: "LogicalOp"):
        self.inputs: List[LogicalOp] = list(inputs)

    @property
    def input(self) -> "LogicalOp":
        return self.inputs[0]

    def with_inputs(self, inputs: Sequence["LogicalOp"]) -> "LogicalOp":
        node = copy.copy(self)
        node.inputs = list(inputs)
        return node

    def label(self) -> str:
        return self.name

    def __repr__(self):
        return self.label()


# ---------------------------------------------------------------------------
# leaves
# ---------------------------------------------------------------------------


class Read(LogicalOp):
    """Leaf over a Datasource (datasource.py): the pushdown surface.
    Projection/predicate rules rewrite `datasource` in place of wrapping
    nodes; metadata shortcuts ask it for count/schema from footers."""

    name = "Read"

    def __init__(self, datasource):
        super().__init__()
        self.datasource = datasource

    def label(self) -> str:
        return f"Read[{self.datasource.describe()}]"


class InputBlocks(LogicalOp):
    """Leaf of already-computed block ObjectRefs (a materialized dataset)."""

    name = "InputBlocks"

    def __init__(self, refs: List[Any]):
        super().__init__()
        self.refs = list(refs)

    def label(self) -> str:
        return f"InputBlocks[{len(self.refs)} blocks]"


# ---------------------------------------------------------------------------
# row transforms (fusable)
# ---------------------------------------------------------------------------


def _fn_name(fn) -> str:
    return getattr(fn, "__name__", None) or type(fn).__name__


class AbstractMap(LogicalOp):
    """A per-block transform that fuses into one task chain.
    `row_preserving` marks 1:1 ops (map/project): the only kinds allowed
    to ride a fused chain past a limit — anything that can change row
    counts must run behind the stream-order fence (ADVICE r5 #1)."""

    row_preserving = False

    def fused_ops(self) -> List[FusedOp]:
        raise NotImplementedError


class MapBatches(AbstractMap):
    name = "MapBatches"

    def __init__(self, input_op: LogicalOp, fn: Callable):
        super().__init__(input_op)
        self.fn = fn

    def fused_ops(self):
        return [("map_batches", self.fn)]

    def label(self):
        return f"MapBatches[{_fn_name(self.fn)}]"


class MapRows(AbstractMap):
    name = "Map"
    row_preserving = True

    def __init__(self, input_op: LogicalOp, fn: Callable):
        super().__init__(input_op)
        self.fn = fn

    def fused_ops(self):
        return [("map", self.fn)]

    def label(self):
        return f"Map[{_fn_name(self.fn)}]"


class Filter(AbstractMap):
    """Row filter: a Python callable OR a structured column predicate
    (`expr`). Only the structured form is visible to predicate pushdown —
    a lambda is opaque."""

    name = "Filter"

    def __init__(self, input_op: LogicalOp, fn: Optional[Callable] = None,
                 expr: Optional[List[tuple]] = None):
        super().__init__(input_op)
        if (fn is None) == (expr is None):
            raise ValueError("Filter takes exactly one of fn / expr")
        self.fn = fn
        self.expr = expr

    def fused_ops(self):
        if self.expr is not None:
            return [("filter_expr", self.expr)]
        return [("filter", self.fn)]

    def label(self):
        if self.expr is not None:
            return f"Filter[{self.expr}]"
        return f"Filter[{_fn_name(self.fn)}]"


class FlatMap(AbstractMap):
    name = "FlatMap"

    def __init__(self, input_op: LogicalOp, fn: Callable):
        super().__init__(input_op)
        self.fn = fn

    def fused_ops(self):
        return [("flat_map", self.fn)]

    def label(self):
        return f"FlatMap[{_fn_name(self.fn)}]"


class Project(AbstractMap):
    """Column selection. Projection pushdown folds this into
    `read_parquet(columns=)` / `read_sql` column lists."""

    name = "Project"
    row_preserving = True

    def __init__(self, input_op: LogicalOp, columns: List[str]):
        super().__init__(input_op)
        self.columns = list(columns)

    def fused_ops(self):
        return [("project", list(self.columns))]

    def label(self):
        return f"Project[{', '.join(self.columns)}]"


class FusedMap(AbstractMap):
    """The fusion rule's output: an adjacent run of map-like nodes
    collapsed into one op chain = ONE remote task per block."""

    name = "FusedMap"

    def __init__(self, input_op: LogicalOp, ops: List[FusedOp],
                 labels: List[str]):
        super().__init__(input_op)
        self.ops = list(ops)
        self.labels = list(labels)

    @property
    def row_preserving(self):
        return all(k in ("map", "project", "limit") for k, _ in self.ops)

    def fused_ops(self):
        return list(self.ops)

    def label(self):
        return f"FusedMap[{' -> '.join(self.labels)}]"


class ActorPoolMap(LogicalOp):
    """Stateful map_batches through an (auto-scaling) actor pool
    (reference: actor_pool_map_operator.py). Never fuses with task ops."""

    name = "ActorPoolMap"

    def __init__(self, input_op: LogicalOp, udf_cls, fn_args: tuple,
                 fn_kwargs: dict, concurrency):
        super().__init__(input_op)
        self.udf_cls = udf_cls
        self.fn_args = tuple(fn_args)
        self.fn_kwargs = dict(fn_kwargs)
        self.concurrency = concurrency

    def stage(self):
        return ("actors", self.udf_cls, self.fn_args, self.fn_kwargs,
                self.concurrency)

    def label(self):
        return (f"ActorPoolMap[{_fn_name(self.udf_cls)}, "
                f"concurrency={self.concurrency}]")


# ---------------------------------------------------------------------------
# limit / multi-input / reorganization
# ---------------------------------------------------------------------------


class Limit(LogicalOp):
    """First-n-rows in stream order. The planner compiles this into (a) a
    per-block cap fused into the task chain, (b) a global stream-order cut
    wherever blocks surface, and (c) covering-prefix execution — only the
    producer prefix whose rows cover n is ever submitted."""

    name = "Limit"

    def __init__(self, input_op: LogicalOp, n: int):
        super().__init__(input_op)
        self.n = int(n)

    def label(self):
        return f"Limit[{self.n}]"


class Union(LogicalOp):
    """Plan-level concatenation: each branch's producers (with their own
    pending chains baked into closures) join one producer list — no
    driver row round-trip, no forced materialization."""

    name = "Union"

    def __init__(self, *branches: LogicalOp):
        super().__init__(*branches)

    def label(self):
        return f"Union[{len(self.inputs)} branches]"


class Materializing(LogicalOp):
    """Base for all-to-all ops (reference: logical AbstractAllToAll): the
    physical planner executes these to block refs (cached on the node, so
    every dataset sharing the subtree reuses the shuffle)."""

    def __init__(self, *inputs: LogicalOp):
        super().__init__(*inputs)
        # shared mutable cell so with_inputs copies share the execution
        self._cache: dict = {}


class Repartition(Materializing):
    name = "Repartition"

    def __init__(self, input_op: LogicalOp, num_blocks: int):
        super().__init__(input_op)
        self.num_blocks = int(num_blocks)

    def label(self):
        return f"Repartition[{self.num_blocks}]"


class Sort(Materializing):
    name = "Sort"

    def __init__(self, input_op: LogicalOp, key: str, descending: bool):
        super().__init__(input_op)
        self.key = key
        self.descending = descending

    def label(self):
        return f"Sort[{self.key}{', desc' if self.descending else ''}]"


class RandomShuffle(Materializing):
    name = "RandomShuffle"

    def __init__(self, input_op: LogicalOp, seed):
        super().__init__(input_op)
        self.seed = seed

    def label(self):
        return f"RandomShuffle[seed={self.seed}]"


class GroupByAgg(Materializing):
    name = "GroupByAgg"

    def __init__(self, input_op: LogicalOp, key: str, agg: str,
                 col: Optional[str]):
        super().__init__(input_op)
        self.key = key
        self.agg = agg
        self.col = col

    def label(self):
        return f"GroupByAgg[{self.key}: {self.agg}({self.col or ''})]"


class Join(Materializing):
    name = "Join"

    def __init__(self, left: LogicalOp, right: LogicalOp, on: str,
                 how: str, num_partitions: Optional[int]):
        super().__init__(left, right)
        self.on = on
        self.how = how
        self.num_partitions = num_partitions

    def label(self):
        return f"Join[{self.how} on {self.on}]"


class Zip(Materializing):
    name = "Zip"

    def __init__(self, left: LogicalOp, right: LogicalOp):
        super().__init__(left, right)

    def label(self):
        return "Zip"


def walk(node: LogicalOp):
    """Pre-order traversal of a plan tree. Iterative: plans grow one node
    per transform call, so chains can be deeper than the Python recursion
    limit."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        stack.extend(reversed(n.inputs))


def render_tree(node: LogicalOp, indent: int = 0) -> List[str]:
    lines: List[str] = []
    stack = [(node, indent)]
    while stack:
        n, d = stack.pop()
        lines.append("  " * d + n.label())
        stack.extend((c, d + 1) for c in reversed(n.inputs))
    return lines
