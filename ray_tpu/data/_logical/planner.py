"""Physical planner: compile an optimized logical plan into executable
segments, and execute them.

Reference surface: python/ray/data/_internal/planner/planner.py:230 (logical
op -> physical operator compilation) + execution/operators/* (task-pool map,
actor-pool map, limit, all-to-all). Here a plan compiles to a list of
`Segment`s:

    Segment = (source producers, pipeline stages, stream-order row limit)

One segment is a fully streamable pipeline: ONE fused remote task per
source block (plus actor-pool stages), executed by StreamingExecutorV2 for
consumption or `_Pipeline` for materialization. Segment boundaries are
stream-order limit FENCES: a row-count-changing op chained after `limit(n)`
lands in the NEXT segment, so it only ever observes rows within the global
budget (ADVICE r5 #1) — the planner derives the fence from the plan shape
instead of the old hand-wired `_limit_src` special case.

A limited segment always executes as a COVERING PREFIX: producers are
submitted in stream-order windows and submission stops once the row budget
is met, so `limit(k)` over B blocks runs O(blocks-needed) tasks.

All-to-all ops (sort/shuffle/groupby/join/zip/repartition) execute to block
refs through the node executors at the bottom of this module (moved from
Dataset methods); their results cache on the logical node, so every dataset
sharing the subtree reuses the shuffle.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.data._logical import operators as ops_mod
from ray_tpu.data.block import (
    Block,
    block_concat,
    block_num_rows,
    block_rows,
    block_slice,
    rows_to_block,
)

_Stage = Tuple

def _count_meta_shortcut(kind: str) -> None:
    try:
        from ray_tpu.util.metrics import get_or_create_counter

        get_or_create_counter(
            "rt_data_meta_shortcuts_total",
            "Dataset queries answered from metadata with zero block "
            "reads", tag_keys=("kind",)).inc(1, tags={"kind": kind})
    except Exception:  # noqa: BLE001 — metrics must never fail a query
        pass


# ---------------------------------------------------------------------------
# segments
# ---------------------------------------------------------------------------


class _DeferredSource:
    """A segment source that resolves to block refs on first execution
    (all-to-all node outputs, baked union branches). explain() renders the
    label without resolving."""

    def __init__(self, label: str, thunk: Optional[Callable] = None):
        self.label = label
        self.thunk = thunk

    def resolve(self) -> List[Any]:
        if self.thunk is None:
            raise RuntimeError(
                f"deferred source {self.label!r} compiled for explain only")
        return self.thunk()


class Segment:
    """One streamable pipeline: source -> stages -> (limit cut)."""

    __slots__ = ("source", "stages", "limit")

    def __init__(self, source=None, stages: Optional[List[_Stage]] = None,
                 limit: Optional[int] = None):
        self.source = source  # list | _DeferredSource | None (stream-fed)
        self.stages: List[_Stage] = list(stages or [])
        self.limit = limit

    def trailing_ops(self) -> List:
        if not self.stages or self.stages[-1][0] != "tasks":
            self.stages.append(("tasks", []))
        return self.stages[-1][1]

    def has_actor_stage(self) -> bool:
        return any(st[0] == "actors" for st in self.stages)

    def resolve_source(self) -> List[Any]:
        if isinstance(self.source, _DeferredSource):
            return self.source.resolve()
        return list(self.source or [])


# ---------------------------------------------------------------------------
# compilation
# ---------------------------------------------------------------------------


def compile_plan(root: ops_mod.LogicalOp, *,
                 allow_execute: bool = True) -> List[Segment]:
    """Compile a (logical) plan to segments. With allow_execute=False the
    compile is side-effect-free — all-to-all sources and limited union
    branches stay symbolic (for explain()). The linear chain is peeled
    ITERATIVELY (plans grow one node per transform call, so chains can be
    deeper than the recursion limit); only Union branches recurse."""
    chain: List[ops_mod.LogicalOp] = []
    node = root
    while isinstance(node, (ops_mod.AbstractMap, ops_mod.ActorPoolMap,
                            ops_mod.Limit)):
        chain.append(node)
        node = node.input

    if isinstance(node, ops_mod.Read):
        segs = [Segment(list(node.datasource.producers()))]
    elif isinstance(node, ops_mod.InputBlocks):
        segs = [Segment(list(node.refs))]
    elif isinstance(node, ops_mod.Union):
        producers: List[Any] = []
        for branch in node.inputs:
            bsegs = compile_plan(branch, allow_execute=allow_execute)
            producers.extend(
                _branch_producers(bsegs, allow_execute=allow_execute))
        segs = [Segment(producers)]
    elif isinstance(node, ops_mod.Materializing):
        if allow_execute:
            src = _DeferredSource(node.label(),
                                  lambda n=node: execute_node(n))
        else:
            src = _DeferredSource(node.label())
        segs = [Segment(src)]
    else:
        raise TypeError(f"cannot compile logical node {node!r}")

    for nd in reversed(chain):
        last = segs[-1]
        if isinstance(nd, ops_mod.ActorPoolMap):
            if last.limit is None:
                last.stages.append(nd.stage())
            else:
                segs.append(Segment(None, [nd.stage()]))
        elif isinstance(nd, ops_mod.Limit):
            if last.limit is None:
                last.limit = nd.n
            else:
                # a second cut of an already-cut stream (an intervening
                # row-preserving op kept it in this segment)
                last.limit = min(last.limit, nd.n)
            # per-block cap pushes down into the fused task chain
            last.trailing_ops().append(("limit", nd.n))
        else:  # AbstractMap
            fused = nd.fused_ops()
            if last.limit is not None:
                if nd.row_preserving:
                    # 1:1 ops may ride the capped chain past a limit: the
                    # per-block cap + the surface stream cut keep the
                    # output exact, and a row-preserving op can't leak
                    # rows past the global budget
                    last.trailing_ops().extend(fused)
                else:
                    # stream-order fence: this op only sees the capped
                    # stream
                    segs.append(Segment(None, [("tasks", list(fused))]))
            else:
                # one stage per LOGICAL node: fusion is the OperatorFusion
                # rule's job (it emits multi-op FusedMap nodes), not the
                # compiler's — with the optimizer off each op really is
                # its own task hop, which is what bench_data.py A/Bs
                last.stages.append(("tasks", list(fused)))
    return segs


def _branch_producers(segs: List[Segment], *,
                      allow_execute: bool) -> List[Any]:
    """A union branch as plain producers: a single task-only unlimited
    segment rides as closures (its pending chain bakes into each
    producer); anything with a limit fence or actor stage bakes to refs."""
    import functools

    from ray_tpu.data.dataset import _run_chain

    if (len(segs) == 1 and segs[0].limit is None
            and not segs[0].has_actor_stage()
            and not isinstance(segs[0].source, _DeferredSource)):
        seg = segs[0]
        chain_ops = [op for st in seg.stages for op in st[1]]
        src = list(seg.source or [])
        if not chain_ops:
            return src
        return [functools.partial(_run_chain, p, chain_ops) for p in src]
    if not allow_execute:
        return [_DeferredSource("union-branch[baked]")]
    refs, _ = execute_to_refs(segs, tag=None)
    return refs


# ---------------------------------------------------------------------------
# execution: materialize path
# ---------------------------------------------------------------------------


def _truncate_block(block: Block, n: int) -> Block:
    # module-level so RemoteFunction(_truncate_block) pickles by reference
    return block_slice(block, 0, n)


def _row_counts(refs: List[Any]) -> List[int]:
    import ray_tpu
    from ray_tpu.remote_function import RemoteFunction

    count = RemoteFunction(block_num_rows)
    return ray_tpu.get([count.remote(r) for r in refs], timeout=600)


class _Pipeline:
    """Executable form of one segment: source producers + stage list.
    Submits ONE chained ref pipeline per source block; actor stages route
    through their pool.

    Pools here are FIRE-AND-FORGET: the caller submits every block before
    any resolves and shuts the pools down right after its barrier, so no
    task_done feedback flows and least-loaded routing degrades to
    submission-count balancing (which is uniform). The streaming executor
    (_executor.StreamingExecutorV2) is the path with live load feedback."""

    def __init__(self, producers, stages: List[_Stage]):
        from ray_tpu.data._executor import AutoScalingActorPool
        from ray_tpu.data.dataset import _run_chain
        from ray_tpu.remote_function import RemoteFunction

        self.producers = producers
        self.stages = stages
        self._run = RemoteFunction(_run_chain)
        self._pools: List[Optional[AutoScalingActorPool]] = []
        for st in stages:
            if st[0] == "actors":
                _, cls, args, kwargs, size = st
                if isinstance(size, tuple):  # (min, max) autoscaling spec
                    size = size[1]
                # fixed-size pool (the materialize path has no scheduling
                # loop to drive scaling); the streaming executor autoscales
                self._pools.append(
                    AutoScalingActorPool(cls, args, kwargs, size, size))
            else:
                self._pools.append(None)

    def submit_block(self, producer):
        """Chain the whole stage pipeline for one source block; returns
        the final block ref. No barriers — downstream stages start as soon
        as their input ref resolves."""
        from ray_tpu._private.core_worker import ObjectRef

        ref = producer
        materialized = isinstance(ref, ObjectRef)
        for st, pool in zip(self.stages, self._pools):
            if st[0] == "tasks":
                if st[1] or not materialized:
                    ref = self._run.remote(ref, st[1])
                    materialized = True
            else:
                if not materialized:
                    # actor stage first: actors take BLOCKS, so a callable
                    # source materializes through one producer task
                    ref = self._run.remote(ref, [])
                    materialized = True
                ref = pool.submit(ref)
        if not materialized:
            ref = self._run.remote(ref, [])
        return ref

    def has_pools(self) -> bool:
        return any(p is not None for p in self._pools)

    def shutdown(self):
        for p in self._pools:
            if p is not None:
                p.shutdown()


def _pipeline_refs(source: List[Any], stages: List[_Stage]) -> List[Any]:
    import ray_tpu
    from ray_tpu._private.core_worker import ObjectRef

    stages = stages or [("tasks", [])]
    if all(st == ("tasks", []) for st in stages) and all(
            isinstance(p, ObjectRef) for p in source):
        return list(source)  # already-computed blocks, nothing to run
    pipeline = _Pipeline(source, stages)
    refs = [pipeline.submit_block(p) for p in source]
    if pipeline.has_pools():
        # actor pools must outlive their in-flight blocks
        ray_tpu.wait(refs, num_returns=len(refs), timeout=None)
    pipeline.shutdown()
    return refs


def _limited_prefix_refs(source: List[Any], stages: List[_Stage],
                         n: int) -> List[Any]:
    """Execute a limited segment over the shortest source prefix whose
    rows cover `n`, in submission windows: count each window's output and
    stop before the next window once the budget is met. Blocks past the
    boundary are never submitted — limit(k) over B blocks runs
    O(blocks-needed) tasks, not B."""
    from ray_tpu.data.context import DataContext
    from ray_tpu.remote_function import RemoteFunction

    window = max(1, DataContext.get_current().streaming_block_window)
    cut = RemoteFunction(_truncate_block)
    pipeline = _Pipeline(source, stages or [("tasks", [])])
    out: List[Any] = []
    remaining = n
    try:
        for start in range(0, len(source), window):
            if remaining <= 0:
                break
            batch = [
                pipeline.submit_block(p)
                for p in source[start:start + window]
            ]
            # the count barrier doubles as the pools'
            # must-outlive-in-flight-blocks barrier per window
            counts = _row_counts(batch)
            for ref, c in zip(batch, counts):
                if remaining <= 0:
                    break  # computed past the boundary; dropped
                if c <= remaining:
                    out.append(ref)
                    remaining -= c
                else:
                    out.append(cut.remote(ref, remaining))
                    remaining = 0
    finally:
        # safe here: every pool-produced block resolved at its window's
        # count barrier; the boundary cut is a plain task over an
        # already-computed ref, so it survives pool shutdown
        pipeline.shutdown()
    return out


def _segment_name(seg: Segment) -> str:
    from ray_tpu.data._executor import _stage_name

    names = [_stage_name(st) for st in seg.stages] or ["read"]
    return " | ".join(names)


def execute_to_refs(segments: List[Segment], *, tag: Optional[str] = ""):
    """Materialize a compiled plan: run each segment in order (a limited
    segment executes its covering prefix), feeding the next segment's
    pipeline with the previous one's refs. Returns (refs, DatasetStats)
    — per-segment op rows threaded into the stats/metrics plane."""
    from ray_tpu.data._executor import DatasetStats, OpStats, record_stats

    t0 = time.perf_counter()
    stats = DatasetStats()
    refs: List[Any] = []
    for i, seg in enumerate(segments):
        seg_t0 = time.perf_counter()
        source = seg.resolve_source() if i == 0 else refs
        if seg.limit is not None:
            refs = _limited_prefix_refs(source, seg.stages, seg.limit)
        else:
            refs = _pipeline_refs(source, seg.stages)
        op = OpStats(name=_segment_name(seg))
        op.blocks = len(refs)
        op.task_s_total = time.perf_counter() - seg_t0
        stats.ops.append(op)
    stats.output_blocks = len(refs)
    stats.wall_s = time.perf_counter() - t0
    if tag is not None:
        from ray_tpu.data._executor import _exec_counter

        record_stats(tag or f"ds-{next(_exec_counter)}", stats)
    return refs, stats


def plan_refs(node: ops_mod.LogicalOp) -> List[Any]:
    """Execute an arbitrary subplan to block refs."""
    return execute_to_refs(compile_plan(node), tag=None)[0]


# ---------------------------------------------------------------------------
# execution: streaming path
# ---------------------------------------------------------------------------


def _cut_stream(blocks, budget: Optional[int]):
    """Stream-order global limit: truncate the boundary block and stop
    pulling upstream once the budget is spent."""
    if budget is None:
        yield from blocks
        return
    for block in blocks:
        if budget <= 0:
            return
        rows = block_num_rows(block)
        if rows > budget:
            yield _truncate_block(block, budget)
            return
        budget -= rows
        yield block


def iter_plan(segments: List[Segment], *, window: int,
              holder: Optional[dict] = None):
    """Streaming consumption of a compiled plan. Segment 0 streams through
    StreamingExecutorV2 under its byte budgets; post-fence segments apply
    their (task-only) chains to the capped stream. A post-fence actor
    stage can't run driver-side, so that rare shape falls back to the
    materialize path."""
    import ray_tpu

    from ray_tpu.data.dataset import _apply_ops

    if any(seg.has_actor_stage() for seg in segments[1:]):
        refs, stats = execute_to_refs(segments)
        if holder is not None:
            holder["stats"] = stats
        yield from _cut_stream(
            (ray_tpu.get(r, timeout=600) for r in refs), None)
        return

    seg0 = segments[0]
    source = seg0.resolve_source()
    from ray_tpu.data._executor import StreamingExecutorV2

    ex = StreamingExecutorV2(source, seg0.stages or [("tasks", [])],
                             window=window)
    try:
        stream = _cut_stream(iter(ex), seg0.limit)
        for seg in segments[1:]:
            chain_ops = [op for st in seg.stages for op in st[1]]
            stream = _cut_stream(
                (_apply_ops(b, chain_ops) for b in stream), seg.limit)
        yield from stream
    finally:
        if holder is not None:
            holder["stats"] = getattr(ex, "last_stats", None)


# ---------------------------------------------------------------------------
# explain rendering
# ---------------------------------------------------------------------------


def describe_segments(segments: List[Segment]) -> List[str]:
    from ray_tpu.data._executor import _actor_label

    lines: List[str] = []
    for i, seg in enumerate(segments):
        if i == 0:
            if isinstance(seg.source, _DeferredSource):
                lines.append(f"  source[{seg.source.label}]")
            else:
                n = len(seg.source or [])
                deferred = sum(
                    1 for p in (seg.source or [])
                    if isinstance(p, _DeferredSource))
                lines.append(
                    f"  source[{n} blocks"
                    + (f", {deferred} baked branch(es)" if deferred else "")
                    + "]")
        for st in seg.stages:
            if st[0] == "tasks":
                names = [k for k, _ in st[1]] or ["read"]
                lines.append(f"  tasks[fused: {' -> '.join(names)}]")
            else:
                lines.append(f"  actors[{_actor_label(st[1])}, "
                             f"concurrency={st[4]}]")
        if seg.limit is not None:
            if i < len(segments) - 1:
                lines.append(
                    f"  limit[stream-order fence: {seg.limit} rows]")
            else:
                lines.append(f"  limit[{seg.limit} rows]")
    return lines


# ---------------------------------------------------------------------------
# metadata shortcuts (zero data blocks read)
# ---------------------------------------------------------------------------


def resolve_count(node: ops_mod.LogicalOp) -> Optional[int]:
    """Row count from plan structure + datasource metadata (parquet
    footers, range/from_items arithmetic) — None means 'must execute'.
    Iterative descent (chains can out-depth the recursion limit); only
    Union branches recurse."""
    limit: Optional[int] = None
    while True:
        if isinstance(node, ops_mod.Read):
            base = node.datasource.count_rows()
            break
        if isinstance(node, ops_mod.Limit):
            limit = node.n if limit is None else min(limit, node.n)
            node = node.input
            continue
        if isinstance(node, ops_mod.AbstractMap):
            if not node.row_preserving:
                return None
            node = node.input
            continue
        if isinstance(node, ops_mod.Union):
            total = 0
            for branch in node.inputs:
                c = resolve_count(branch)
                if c is None:
                    return None
                total += c
            base = total
            break
        if isinstance(node, (ops_mod.Repartition, ops_mod.Sort,
                             ops_mod.RandomShuffle)):
            node = node.input
            continue
        return None
    if base is None:
        return None
    return base if limit is None else min(base, limit)


def resolve_schema(node: ops_mod.LogicalOp) -> Optional[Dict[str, str]]:
    projects: List[List[str]] = []  # collected outermost-first
    while True:
        if isinstance(node, ops_mod.Read):
            sch = node.datasource.schema()
            break
        if isinstance(node, ops_mod.Project):
            projects.append(node.columns)
            node = node.input
            continue
        if isinstance(node, (ops_mod.Filter, ops_mod.Limit,
                             ops_mod.Repartition, ops_mod.Sort,
                             ops_mod.RandomShuffle)):
            node = node.input
            continue
        if isinstance(node, ops_mod.Union):
            schemas = [resolve_schema(b) for b in node.inputs]
            if all(s is not None for s in schemas) and all(
                    s == schemas[0] for s in schemas):
                sch = schemas[0]
                break
            return None
        return None
    if sch is None:
        return None
    for cols in reversed(projects):  # apply innermost projection first
        try:
            sch = {c: sch[c] for c in cols}
        except KeyError:
            return None
    return sch


def resolve_num_blocks(node: ops_mod.LogicalOp) -> Optional[int]:
    while isinstance(node, (ops_mod.AbstractMap, ops_mod.ActorPoolMap,
                            ops_mod.Limit)):
        node = node.input
    if isinstance(node, ops_mod.Read):
        return node.datasource.num_blocks()
    if isinstance(node, ops_mod.InputBlocks):
        return len(node.refs)
    if isinstance(node, ops_mod.Union):
        total = 0
        for branch in node.inputs:
            c = resolve_num_blocks(branch)
            if c is None:
                return None
            total += c
        return total
    if isinstance(node, ops_mod.Repartition):
        return node.num_blocks
    return None


def projection_folded(node: ops_mod.LogicalOp) -> bool:
    """True when an optimized plan carries no residual Project AND some
    datasource accepted a column pushdown — i.e. projecting actually
    narrows the read instead of adding a per-block copy."""
    has_project = any(
        isinstance(n, ops_mod.Project)
        or (isinstance(n, ops_mod.FusedMap)
            and any(k == "project" for k, _ in n.ops))
        for n in ops_mod.walk(node))
    pushed = any(
        isinstance(n, ops_mod.Read) and n.datasource.columns
        for n in ops_mod.walk(node))
    return pushed and not has_project


def record_metadata_stats(dataset_tag: str, kind: str, detail: str):
    """A query answered with zero data blocks read still shows up on the
    stats/metrics plane (the test surface for 'no map tasks ran')."""
    from ray_tpu.data._executor import (DatasetStats, OpStats, _exec_counter,
                                        record_stats)

    st = DatasetStats(ops=[OpStats(name=f"metadata[{kind}: {detail}]")])
    record_stats(dataset_tag or f"ds-{next(_exec_counter)}", st,
                 emit_metrics=False)
    _count_meta_shortcut(kind)
    return st


# ---------------------------------------------------------------------------
# all-to-all node executors (moved from Dataset methods)
# ---------------------------------------------------------------------------


def _stable_key_hash(v) -> int:
    """Deterministic cross-process key hash for shuffles/joins. NOT hash():
    str hashing is per-process randomized. Numeric keys canonicalize first
    (1, 1.0, np.int64(1), True are dict-equal and must co-partition)."""
    import hashlib as _hl

    if hasattr(v, "item"):
        v = v.item()
    if isinstance(v, bool):
        v = int(v)
    if isinstance(v, float) and v.is_integer():
        v = int(v)
    d = _hl.blake2b(repr(v).encode(), digest_size=8).digest()
    return int.from_bytes(d, "little")


def _shuffle_partitions(refs, requested: Optional[int] = None) -> int:
    """Partition count for shuffle-class ops (sort/shuffle/groupby/join).

    Spill-aware sizing (reference: the shuffle partitioning in
    execution/operators/hash_shuffle + resource_manager budgets): target
    ~shuffle_target_partition_bytes per partition from SAMPLED block sizes,
    capped at shuffle_max_partitions — without the cap, B input blocks x
    B partitions costs B^2 return refs and B-arg merge tasks, which is what
    falls over at hundreds of blocks, not the O(N) data movement."""
    if requested:
        return max(1, int(requested))
    n = len(refs)
    if n <= 1:
        return max(1, n)
    from ray_tpu.data.context import DataContext

    ctx = DataContext.get_current()
    target = ctx.shuffle_target_partition_bytes
    cap = ctx.shuffle_max_partitions
    from ray_tpu.data._executor import _ref_size

    # strided sample: leading blocks are often unrepresentative (header /
    # remainder blocks from readers)
    probe = refs[::max(1, n // 8)][:8]
    sizes = [sz for sz in (_ref_size(r) for r in probe) if sz is not None]
    if sizes:
        est_total = (sum(sizes) / len(sizes)) * n
        want = -(-int(est_total) // max(1, target))
        return max(1, min(n, cap, max(want, 1)))
    return max(1, min(n, cap))


def _slice_row_range(lo: int, hi: int, block_starts, *blocks) -> Block:
    """Rows [lo, hi) of a virtual concatenation, given each block's global
    start offset (shared by repartition and zip alignment)."""
    parts = []
    for s, b in zip(block_starts, blocks):
        n = block_num_rows(b)
        a, z = max(lo, s), min(hi, s + n)
        if z > a:
            parts.append(block_slice(b, a - s, z - s))
    return block_concat(parts) if parts else rows_to_block([])


def _sort_block(block: Block, key: str, descending: bool) -> Block:
    if isinstance(block, dict):
        col = np.asarray(block[key])
        order = np.argsort(col, kind="stable")
        if descending:
            order = order[::-1]
        return {c: np.asarray(v)[order] for c, v in block.items()}
    rows = sorted(block_rows(block), key=lambda r: r[key], reverse=descending)
    return rows_to_block(rows)


def execute_node(node: ops_mod.Materializing) -> List[Any]:
    """Execute an all-to-all node to block refs (cached on the node)."""
    cache = node._cache
    if "refs" in cache:
        return cache["refs"]
    if isinstance(node, ops_mod.Repartition):
        refs = execute_repartition(plan_refs(node.input), node.num_blocks)
    elif isinstance(node, ops_mod.Sort):
        refs = execute_sort(plan_refs(node.input), node.key, node.descending)
    elif isinstance(node, ops_mod.RandomShuffle):
        refs = execute_random_shuffle(plan_refs(node.input), node.seed)
    elif isinstance(node, ops_mod.GroupByAgg):
        refs = execute_groupby(plan_refs(node.input), node.key, node.agg,
                               node.col)
    elif isinstance(node, ops_mod.Join):
        refs = execute_join(plan_refs(node.inputs[0]),
                            plan_refs(node.inputs[1]), node.on, node.how,
                            node.num_partitions)
    elif isinstance(node, ops_mod.Zip):
        refs = execute_zip(plan_refs(node.inputs[0]),
                           plan_refs(node.inputs[1]))
    else:
        raise TypeError(f"no executor for {node!r}")
    cache["refs"] = refs
    return refs


def execute_repartition(refs: List[Any], num_blocks: int) -> List[Any]:
    """Rebalance rows into `num_blocks` equal blocks. Each output task
    receives only the input blocks overlapping its row range — O(N) total
    movement, not all-blocks-to-every-task."""
    from ray_tpu.remote_function import RemoteFunction

    counts = _row_counts(refs)
    starts = list(np.cumsum([0] + counts))  # global start offset per block
    total = starts[-1]

    run = RemoteFunction(_slice_row_range)
    new_refs = []
    for i in range(num_blocks):
        lo, hi = (total * i) // num_blocks, (total * (i + 1)) // num_blocks
        overlap = [
            j for j in range(len(refs))
            if starts[j] < hi and starts[j] + counts[j] > lo
        ]
        new_refs.append(run.remote(
            lo, hi, [starts[j] for j in overlap], *[refs[j] for j in overlap]
        ))
    return new_refs


def execute_random_shuffle(refs: List[Any], seed) -> List[Any]:
    """Global random shuffle. Two-stage push shuffle as in the reference's
    shuffle ops: each input block scatters its rows into k partitions (one
    task, k returns); each output concatenates and permutes its k incoming
    parts — O(N) total movement."""
    from ray_tpu.remote_function import RemoteFunction

    k = _shuffle_partitions(refs)
    if len(refs) <= 1:
        return list(refs)

    def _scatter(sd, j: int, k: int, block):
        rng = np.random.default_rng(None if sd is None else sd * 1_000_003 + j)
        n = block_num_rows(block)
        assign = rng.integers(0, k, size=n)
        if isinstance(block, dict):
            return tuple(
                {c: v[assign == i] for c, v in block.items()} for i in range(k)
            )
        items = list(block)
        return tuple(
            [items[t] for t in np.flatnonzero(assign == i)] for i in range(k)
        )

    def _merge(sd, i: int, *parts):
        whole = block_concat(list(parts))
        rng = np.random.default_rng(None if sd is None else sd * 7_000_003 + i)
        n = block_num_rows(whole)
        perm = rng.permutation(n)
        if isinstance(whole, dict):
            return {c: v[perm] for c, v in whole.items()}
        return [whole[j] for j in perm]

    merge = RemoteFunction(_merge)
    if k == 1:
        # size-driven single partition: permute everything in one task
        return [merge.remote(seed, 0, *refs)]
    scatter = RemoteFunction(_scatter).options(num_returns=k)
    # EVERY input block scatters (k is the partition count, which may
    # be smaller than the block count under spill-aware sizing)
    partitions = [
        scatter.remote(seed, j, k, refs[j]) for j in range(len(refs))
    ]
    return [
        merge.remote(seed, i, *[p[i] for p in partitions])
        for i in range(k)
    ]


def _sort_single_partition(refs, key, descending) -> List[Any]:
    """One global sort task (a per-block sort would not be a global order
    when several blocks feed one partition)."""
    from ray_tpu.remote_function import RemoteFunction

    def _sort_all(*blocks):
        return _sort_block(block_concat(list(blocks)), key, descending)

    return [RemoteFunction(_sort_all).remote(*refs)]


def execute_sort(refs: List[Any], key: str, descending: bool) -> List[Any]:
    """Distributed sort: sample key range → range-partition scatter →
    per-partition sort (reference: data sort ops; the classic TeraSort
    shape, O(N) movement + parallel partition sorts)."""
    import ray_tpu
    from ray_tpu.remote_function import RemoteFunction

    k = _shuffle_partitions(refs)
    if not refs:
        return []
    if k == 1:
        # no range bounds needed — skip the sampling round-trip
        return _sort_single_partition(refs, key, descending)

    def _sample(block):
        col = np.asarray(block[key]) if isinstance(block, dict) else (
            np.asarray([r[key] for r in block_rows(block)])
        )
        if col.size == 0:
            return col
        take = min(64, col.size)
        idx = np.random.default_rng(0).choice(col.size, take, replace=False)
        return col[idx]

    samples = np.concatenate([
        s for s in ray_tpu.get(
            [RemoteFunction(_sample).remote(r) for r in refs], timeout=600)
        if s.size
    ])
    if samples.size == 0:
        return _sort_single_partition(refs, key, descending)
    # positional quantiles, not np.quantile: sort keys may be strings
    # (any sortable dtype) and only order matters for range bounds
    srt = np.sort(samples)
    bounds = srt[[
        min(srt.size - 1, max(0, (srt.size * i) // k)) for i in range(1, k)
    ]]

    def _scatter(block, bounds):
        col = np.asarray(block[key]) if isinstance(block, dict) else (
            np.asarray([r[key] for r in block_rows(block)])
        )
        assign = np.searchsorted(bounds, col, side="right")
        n_parts = len(bounds) + 1
        if isinstance(block, dict):
            return tuple(
                {c: np.asarray(v)[assign == i] for c, v in block.items()}
                for i in range(n_parts)
            )
        items = list(block)
        return tuple(
            [items[t] for t in np.flatnonzero(assign == i)]
            for i in range(n_parts)
        )

    def _merge_sort(*parts):
        return _sort_block(block_concat(list(parts)), key, descending)

    scatter = RemoteFunction(_scatter).options(num_returns=k)
    partitions = [scatter.remote(r, bounds) for r in refs]
    order = range(k - 1, -1, -1) if descending else range(k)
    # fan-in over EVERY scatter (len(refs)), not range(k): k may be
    # size-driven < len(refs)
    return [
        RemoteFunction(_merge_sort).remote(*[p[i] for p in partitions])
        for i in order
    ]


# per-group leaf computed inside one partition: hash partitioning puts ALL
# rows of a group in the same partition, so no cross-partition combine is
# needed — mean included
GROUP_AGGS = {
    "count": len,
    "sum": lambda vals: np.sum(vals).item(),
    "min": lambda vals: np.min(vals).item(),
    "max": lambda vals: np.max(vals).item(),
    "mean": lambda vals: float(np.mean(vals)),
}


def execute_groupby(refs: List[Any], key: str, agg: str,
                    col: Optional[str]) -> List[Any]:
    """Hash-partitioned group-by + aggregate (reference: data groupby with
    hash_shuffle aggregate operators). Keys scatter to k partitions by
    hash; each partition aggregates its groups independently."""
    from ray_tpu.remote_function import RemoteFunction

    if not refs:
        return []
    k = _shuffle_partitions(refs)

    def _scatter(block, k):
        keys = (np.asarray(block[key]) if isinstance(block, dict)
                else np.asarray([r[key] for r in block_rows(block)]))
        assign = np.asarray(
            [_stable_key_hash(x) % k for x in keys.tolist()])
        if isinstance(block, dict):
            return tuple(
                {c: np.asarray(v)[assign == i] for c, v in block.items()}
                for i in range(k)
            )
        items = list(block)
        return tuple(
            [items[t] for t in np.flatnonzero(assign == i)]
            for i in range(k)
        )

    def _agg_partition(agg, col, *parts):
        whole = block_concat(list(parts))
        groups: Dict[Any, list] = {}
        for r in block_rows(whole):
            groups.setdefault(r[key], []).append(
                r[col] if col is not None else 1
            )
        leaf = GROUP_AGGS[agg]
        out_name = f"{agg}({col})" if col else "count()"
        return rows_to_block([
            {key: gk, out_name: leaf(vals)} for gk, vals in groups.items()
        ])

    agg_fn = RemoteFunction(_agg_partition)
    if k == 1:
        # no scatter needed — but EVERY block feeds the one partition
        # (k may be size-driven < len(refs))
        return [agg_fn.remote(agg, col, *refs)]
    scatter = RemoteFunction(_scatter).options(num_returns=k)
    partitions = [scatter.remote(r, k) for r in refs]
    # fan-in over EVERY scatter (len(refs) of them), not range(k): k may
    # be size-driven < len(refs)
    return [
        agg_fn.remote(agg, col, *[p[i] for p in partitions])
        for i in range(k)
    ]


def execute_join(left: List[Any], right: List[Any], on: str, how: str,
                 num_partitions: Optional[int]) -> List[Any]:
    """Distributed hash join on column `on` (reference: the data join
    operator / hash_shuffle): both sides scatter rows by hash(key) into
    k partitions (one task per block, k returns), then one task per
    partition builds a hash table from the left rows and probes with the
    right — O(N) movement, k-way parallel joins."""
    from ray_tpu.remote_function import RemoteFunction

    # size BOTH sides: a huge few-block side must not collapse the join
    # because the other side has more (tiny) blocks
    k = (int(num_partitions) if num_partitions
         else max(_shuffle_partitions(left), _shuffle_partitions(right)))

    def _scatter(block, k):
        rows = list(block_rows(block))
        parts: List[List[Any]] = [[] for _ in range(k)]
        for r in rows:
            parts[_stable_key_hash(r[on]) % k].append(r)
        return tuple(rows_to_block(p) for p in parts)

    def _join_partition(n_left, *parts):
        lrows = [r for b in parts[:n_left] for r in block_rows(b)]
        rrows = [r for b in parts[n_left:] for r in block_rows(b)]
        table: Dict[Any, List[Any]] = {}
        for r in rrows:
            table.setdefault(r[on], []).append(r)
        out = []
        for lr in lrows:
            matches = table.get(lr[on])
            if matches:
                for rr in matches:
                    merged = dict(lr)
                    for ck, cv in rr.items():
                        if ck != on:
                            merged[ck if ck not in merged
                                   else f"{ck}_1"] = cv
                    out.append(merged)
            elif how == "left":
                out.append(dict(lr))
        return rows_to_block(out)

    joiner = RemoteFunction(_join_partition)
    if k == 1:
        # num_returns=1 .remote() stores the 1-tuple whole; skip the
        # scatter and hand the raw block refs to the join task (advisor r3)
        return [joiner.remote(len(left), *left, *right)]
    scatter = RemoteFunction(_scatter).options(num_returns=k)
    lparts = [scatter.remote(r, k) for r in left]
    rparts = [scatter.remote(r, k) for r in right]
    return [
        joiner.remote(
            len(lparts),
            *[lp[i] for lp in lparts],
            *[rp[i] for rp in rparts],
        )
        for i in range(k)
    ]


def execute_zip(left: List[Any], right: List[Any]) -> List[Any]:
    """Column-wise zip of two equal-row-count block lists: the right side
    is range-repartitioned to the left's block boundaries, then each
    aligned pair merges columns in one task (duplicate names get a _1
    suffix)."""
    from ray_tpu.remote_function import RemoteFunction

    counts = _row_counts(left)
    r_counts = _row_counts(right)
    if sum(counts) != sum(r_counts):
        raise ValueError(
            f"zip needs equal row counts: {sum(counts)} vs {sum(r_counts)}")
    r_starts = list(np.cumsum([0] + r_counts))

    def _zip_blocks(a, b):
        if not isinstance(a, dict) or not isinstance(b, dict):
            return [
                (ra, rb) for ra, rb in zip(block_rows(a), block_rows(b))
            ]
        out = dict(a)
        for k, v in b.items():
            out[k if k not in out else f"{k}_1"] = v
        return out

    slicer = RemoteFunction(_slice_row_range)
    zipper = RemoteFunction(_zip_blocks)
    new_refs = []
    lo = 0
    for ref, n in zip(left, counts):
        hi = lo + n
        overlap = [
            j for j in range(len(right))
            if r_starts[j] < hi and r_starts[j] + r_counts[j] > lo
        ]
        aligned = slicer.remote(
            lo, hi, [r_starts[j] for j in overlap],
            *[right[j] for j in overlap])
        new_refs.append(zipper.remote(ref, aligned))
        lo = hi
    return new_refs
