"""ray_tpu.data._logical — the query-planning subsystem.

Reference surface: python/ray/data/_internal/logical/ (operators + rules +
optimizers) and _internal/planner/planner.py. Three layers:

  operators.py  — the logical node vocabulary Datasets build lazily
  rules.py + optimizer.py — rule-based rewrites applied to fixpoint
                  (fusion, limit/projection/predicate pushdown), every
                  firing recorded for explain()
  planner.py    — compiles the optimized plan to streamable Segments
                  (StreamingExecutorV2 / _Pipeline), executes all-to-all
                  nodes, and answers count/schema/num_blocks from
                  metadata with zero data blocks read
"""

from ray_tpu.data._logical import operators, optimizer, planner, rules

__all__ = ["operators", "optimizer", "planner", "rules"]
