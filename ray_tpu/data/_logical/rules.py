"""Rewrite rules for the logical optimizer.

Reference surface: python/ray/data/_internal/logical/rules/ (operator
fusion, limit pushdown, projection pushdown / column pruning) applied by
`logical/optimizers.py` to fixpoint — the Volcano-style rule pass Graefe's
optimizer generator popularized. Each rule is a pure plan→plan rewrite; the
optimizer records every firing so `Dataset.explain()` can print exactly
which rules shaped the physical plan.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

from ray_tpu.data._logical import operators as ops


def _transform_up(node: ops.LogicalOp,
                  fn: Callable[[ops.LogicalOp], Optional[ops.LogicalOp]],
                  ) -> ops.LogicalOp:
    """Bottom-up rewrite: children first, then `fn` on the (possibly
    rebuilt) node. fn returns a replacement node or None (no match).
    Iterative post-order (explicit stack): plans grow one node per
    transform call, so chains can be deeper than the recursion limit."""
    done: dict = {}  # id(original node) -> rewritten node
    stack = [(node, False)]
    while stack:
        n, children_done = stack.pop()
        if not children_done:
            stack.append((n, True))
            stack.extend((c, False) for c in n.inputs)
            continue
        new_inputs = [done[id(c)] for c in n.inputs]
        rebuilt = n
        if any(a is not b for a, b in zip(new_inputs, n.inputs)):
            rebuilt = n.with_inputs(new_inputs)
        out = fn(rebuilt)
        done[id(n)] = rebuilt if out is None else out
    return done[id(node)]


class Rule:
    """One rewrite. apply() returns (new_root, fired) where fired is a
    human-readable description per match (empty = rule did not fire)."""

    name = "Rule"

    def apply(self, root: ops.LogicalOp
              ) -> Tuple[ops.LogicalOp, List[str]]:
        raise NotImplementedError


class LimitFoldRule(Rule):
    """limit(a) ∘ limit(b) → limit(min(a, b)) — two cuts of one stream."""

    name = "LimitFold"

    def apply(self, root):
        fired: List[str] = []

        def fn(node):
            if isinstance(node, ops.Limit) and isinstance(
                    node.input, ops.Limit):
                inner = node.input
                n = min(node.n, inner.n)
                fired.append(
                    f"{self.name}: limit({inner.n})+limit({node.n}) -> "
                    f"limit({n})")
                return ops.Limit(inner.input, n)
            return None

        return _transform_up(root, fn), fired


class LimitPushdownRule(Rule):
    """Push limit below row-preserving ops (map/project) toward the
    source, in stream order: `map(f).limit(n)` ≡ `limit(n).map(f)` for 1:1
    f, and the closer the limit sits to the read, the shorter the covering
    prefix the planner executes (reference: rules/limit_pushdown.py)."""

    name = "LimitPushdown"

    def apply(self, root):
        fired: List[str] = []

        def fn(node):
            if not (isinstance(node, ops.Limit) and isinstance(
                    node.input, ops.AbstractMap)
                    and node.input.row_preserving):
                return None
            # dataflow: ... -> map -> limit  ==>  ... -> limit -> map.
            # Sink below the WHOLE run of row-preserving ops in one firing:
            # one level per optimizer pass would strand the limit mid-chain
            # once the chain is deeper than the fixpoint pass budget
            run = []
            cur = node.input
            while isinstance(cur, ops.AbstractMap) and cur.row_preserving:
                run.append(cur)
                cur = cur.input
            fired.append(
                f"{self.name}: limit({node.n}) below "
                f"{' + '.join(m.label() for m in run)}")
            new = ops.Limit(cur, node.n)
            for m in reversed(run):
                new = m.with_inputs([new])
            return new

        return _transform_up(root, fn), fired


def _fold_through_limits(node, fold_read):
    """Descend through Limit nodes only (projection commutes with a row
    cut) looking for a foldable Read; returns a rebuilt subtree or None."""
    if isinstance(node, ops.Read):
        return fold_read(node)
    if isinstance(node, ops.Limit):
        inner = _fold_through_limits(node.input, fold_read)
        if inner is not None:
            return ops.Limit(inner, node.n)
    return None


class ProjectionPushdownRule(Rule):
    """Fold Project into a column-capable datasource: read_parquet grows
    `columns=`, read_sql rewrites its column list — the reader then never
    materializes dropped columns (reference: rules/projection_pushdown)."""

    name = "ProjectionPushdown"

    def apply(self, root):
        fired: List[str] = []

        def fn(node):
            if not isinstance(node, ops.Project):
                return None
            if isinstance(node.input, ops.Project):
                inner = node.input
                if not set(node.columns) <= set(inner.columns):
                    # outer names a column the inner projection dropped —
                    # collapsing would resurrect it; leave the plan alone
                    # so execution raises exactly like the unoptimized path
                    return None
                fired.append(
                    f"{self.name}: project∘project -> "
                    f"project({', '.join(node.columns)})")
                return ops.Project(inner.input, node.columns)

            def fold_read(read):
                ds = read.datasource
                if getattr(ds, "supports_column_pushdown", False) and \
                        ds.columns is None:
                    try:
                        pushed = ds.with_columns(node.columns)
                    except ValueError:
                        # datasource can't express these names (e.g. SQL
                        # rejects non-plain identifiers) — leave Project
                        # as a block op instead of failing the plan
                        return None
                    fired.append(
                        f"{self.name}: columns={node.columns} into "
                        f"{ds.describe()}")
                    return ops.Read(pushed)
                return None

            return _fold_through_limits(node.input, fold_read)

        return _transform_up(root, fn), fired


class PredicatePushdownRule(Rule):
    """Fold a structured column predicate (`filter(expr=...)`) directly
    over a Read into the datasource — the parquet reader gets pyarrow
    `filters=` and skips non-matching row groups at the IO layer."""

    name = "PredicatePushdown"

    def apply(self, root):
        fired: List[str] = []

        def fn(node):
            if not (isinstance(node, ops.Filter) and node.expr is not None
                    and isinstance(node.input, ops.Read)):
                return None
            ds = node.input.datasource
            if not getattr(ds, "supports_predicate_pushdown", False):
                return None
            if ds.columns is not None and not \
                    set(ops.expr_columns(node.expr)) <= set(ds.columns):
                # predicate names a column the pushed-down projection
                # dropped — pyarrow would filter on the full file schema
                # and silently succeed where the unoptimized chain errors
                return None
            fired.append(f"{self.name}: {node.expr} into {ds.describe()}")
            return ops.Read(ds.with_filters(node.expr))

        return _transform_up(root, fn), fired


class OperatorFusionRule(Rule):
    """Fuse adjacent map-like nodes into one FusedMap = ONE remote task
    per block (subsumes the old Dataset._chain hand fusion; reference:
    rules/operator_fusion.py). Runs after the pushdown rules so fusion
    never hides a Project/Filter from the datasource fold."""

    name = "OperatorFusion"

    def apply(self, root):
        fired: List[str] = []

        def fn(node):
            if not (isinstance(node, ops.AbstractMap)
                    and isinstance(node.input, ops.AbstractMap)):
                return None
            inner, outer = node.input, node
            in_labels = (inner.labels if isinstance(inner, ops.FusedMap)
                         else [inner.label()])
            out_labels = (outer.labels if isinstance(outer, ops.FusedMap)
                          else [outer.label()])
            fired.append(
                f"{self.name}: {' + '.join(in_labels + out_labels)}")
            return ops.FusedMap(
                inner.input, inner.fused_ops() + outer.fused_ops(),
                in_labels + out_labels)

        return _transform_up(root, fn), fired


# the canonical pass order: semantic folds and pushdowns first (they need
# raw node adjacency), fusion last (it erases adjacency into chains)
REWRITE_RULES = [LimitFoldRule, LimitPushdownRule, ProjectionPushdownRule,
                 PredicatePushdownRule]
FUSION_RULES = [OperatorFusionRule]
