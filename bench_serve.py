"""Serve overload benchmarks: an offered-load sweep of concurrent
streaming HTTP clients through the proxy, A/B'ing the overload plane
(bounded queues + ingress shed + end-to-end deadlines) against the naive
unbounded configuration.

The experiment (reference: DAGOR's overload-control evaluation + the Ray
Serve max_ongoing/max_queued admission docs): a deployment with a fixed
per-request service time gives a known capacity C = replicas x
max_concurrent / service_s. Open-loop clients offer 0.5x / 1x / 2x C for
a fixed window under a client SLO; a request is GOOD only if its stream
completes within the SLO.

- shedding ON: bounded replica queues (max_queued_requests), ingress
  shed, and the SLO propagated as the X-Serve-Timeout-S deadline — the
  server refuses or abandons work whose caller is gone.
- shedding OFF: unbounded queues, no deadline — the server burns
  capacity on requests whose clients have long departed, and queue wait
  pushes later requests past the SLO (goodput collapses at 2x).

Emits one JSON record per (mode, offered-ratio) leg:
{"bench": "serve_overload", "mode": "shed_on"|"shed_off", "offered_x":
 2.0, "offered_rps": ..., "goodput_rps": ..., "p50_ms": ..., "p99_ms":
 ..., "shed_rate": ..., "unit": "req/s"} and writes the collected
artifact (BENCH_SERVE_rNN.json) with --out.

Run: python bench_serve.py [--quick] [--out BENCH_SERVE_r12.json]
"""

import argparse
import asyncio
import json
import time


def _percentile(sorted_vals, p):
    if not sorted_vals:
        return None
    k = min(len(sorted_vals) - 1, int(round(p / 100.0 * (len(sorted_vals) - 1))))
    return sorted_vals[k]


async def _one_request(client, url, slo_s, use_deadline, chunks, t_base):
    import httpx

    headers = {"X-Serve-Timeout-S": str(slo_s)} if use_deadline else {}
    t0 = time.perf_counter()
    try:
        async with client.stream(
                "POST", url, json={"stream": True, "n": chunks},
                headers=headers) as r:
            if r.status_code == 503:
                return ("shed", None, None)
            if r.status_code == 504:
                return ("deadline", None, None)
            if r.status_code != 200:
                return ("error", None, None)
            done = False
            async for line in r.aiter_lines():
                if line.startswith("data: "):
                    body = line[len("data: "):]
                    if body == "[DONE]":
                        done = True
                    elif '"error"' in body:
                        try:
                            kind = json.loads(body).get("type")
                        except ValueError:
                            kind = "error"
                        # a deadline that expires mid-stream is the server
                        # correctly abandoning dead work, not an error;
                        # mid-stream backpressure is a shed
                        if kind == "deadline_exceeded":
                            return ("deadline", None, None)
                        if kind == "backpressure":
                            return ("shed", None, None)
                        return ("server_error", None, None)
            t1 = time.perf_counter()
            if done and t1 - t0 <= slo_s:
                return ("ok", t1 - t0, t1 - t_base)
            return ("late", t1 - t0, None)
    except httpx.TimeoutException:
        return ("timeout", None, None)
    except Exception:  # noqa: BLE001 — connection refused/reset under burst
        return ("error", None, None)


async def _leg(url, rate, duration_s, slo_s, use_deadline, chunks):
    """Open-loop arrivals at `rate` req/s for `duration_s`: arrivals do
    not slow down when the server does — that is what makes overload
    overload (a closed loop would self-throttle and hide the pathology)."""
    import httpx

    limits = httpx.Limits(max_connections=2000,
                          max_keepalive_connections=200)
    timeout = httpx.Timeout(slo_s + 0.5, connect=10.0)
    loop = asyncio.get_running_loop()
    results = []
    t_base = time.perf_counter()
    async with httpx.AsyncClient(limits=limits, timeout=timeout) as client:
        tasks = []
        start = loop.time()
        n = max(1, int(rate * duration_s))
        for i in range(n):
            delay = start + i / rate - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            tasks.append(asyncio.ensure_future(
                _one_request(client, url, slo_s, use_deadline, chunks,
                             t_base)))
        results = await asyncio.gather(*tasks)
    return results


def _drain(base, timeout_s=60.0):
    """Wait for the proxy's in-flight count to hit zero between legs so
    one leg's backlog can't pollute the next measurement."""
    import httpx

    deadline = time.time() + timeout_s
    while time.time() < deadline:
        try:
            hz = httpx.get(f"{base}/-/healthz", timeout=10).json()
            if hz.get("inflight", 1) == 0:
                return True
        except Exception:  # noqa: BLE001
            pass
        time.sleep(0.25)
    return False


def run_suite(quick: bool = False):
    import ray_tpu
    from ray_tpu import serve

    own_cluster = False
    try:
        from ray_tpu._private.worker import is_initialized

        own_cluster = not is_initialized()
    except Exception:  # noqa: BLE001
        own_cluster = True
    if own_cluster:
        ray_tpu.init(num_cpus=8)

    if quick:
        # two legs per mode (1x, 2x): the smoke only needs "no shed at
        # capacity" and "sheds + holds goodput at 2x" — the committed
        # full-size artifact carries the 0.5x point
        replicas, max_concurrent, service_s = 1, 2, 0.08
        duration_s, slo_s, ratios = 1.2, 1.0, (1.0, 2.0)
    else:
        # sized so 2x offered load stays inside what ONE ingress proxy
        # comfortably forwards (~50 rps of SSE dispatch on CPython):
        # the experiment must overload the REPLICA admission plane, not
        # the benchmark's own proxy event loop
        replicas, max_concurrent, service_s = 2, 4, 0.25
        duration_s, slo_s, ratios = 8.0, 1.2, (0.5, 1.0, 2.0)
    chunks = 2
    capacity_rps = replicas * max_concurrent / service_s
    # queue bound sized so accepted-queue wait stays well inside the SLO:
    # max_queued * service_s / max_concurrent <= ~0.2 * SLO
    max_queued = max(2, int(0.2 * slo_s * max_concurrent / service_s))

    def make_deployment(shed_on):
        step = service_s / chunks

        @serve.deployment(
            name="overload_bench", num_replicas=replicas,
            max_concurrent_queries=max_concurrent,
            max_queued_requests=(max_queued if shed_on else -1),
            version=f"bench-{'on' if shed_on else 'off'}")
        class Bench:
            async def __call__(self, payload=None):
                n = int((payload or {}).get("n", chunks))
                for i in range(n):
                    await asyncio.sleep(step)
                    yield {"i": i}

        return Bench

    records = []
    base = None
    for shed_on in (True, False):
        serve.run(make_deployment(shed_on).bind())
        if base is None:
            base = serve.start(http_port=0)
        url = f"{base}/overload_bench"
        # warmup: routes, handle caches, proxy connections
        asyncio.run(_leg(url, rate=max(4.0, capacity_rps / 4),
                         duration_s=0.5 if not quick else 0.3, slo_s=slo_s,
                         use_deadline=shed_on, chunks=chunks))
        _drain(base)
        for x in ratios:
            offered = capacity_rps * x
            results = asyncio.run(_leg(
                url, rate=offered, duration_s=duration_s, slo_s=slo_s,
                use_deadline=shed_on, chunks=chunks))
            counts = {}
            lat = []
            last_done = duration_s
            for kind, dt, t_done in results:
                counts[kind] = counts.get(kind, 0) + 1
                if kind == "ok":
                    lat.append(dt)
                    last_done = max(last_done, t_done)
            lat.sort()
            n = len(results)
            # goodput over the WHOLE completion window, not just the
            # offered window — an unbounded queue finishing its backlog
            # after the leg must not read as extra throughput
            goodput = round(counts.get("ok", 0) / last_done, 1)
            rec = {
                "bench": "serve_overload",
                "mode": "shed_on" if shed_on else "shed_off",
                "offered_x": x,
                "offered_rps": round(offered, 1),
                "capacity_rps": round(capacity_rps, 1),
                "requests": n,
                "goodput_rps": goodput,
                "value": goodput,
                "unit": "req/s",
                "p50_ms": (round(_percentile(lat, 50) * 1000, 1)
                           if lat else None),
                "p99_ms": (round(_percentile(lat, 99) * 1000, 1)
                           if lat else None),
                "shed_rate": round(counts.get("shed", 0) / n, 3),
                "failed_slo_rate": round(
                    (counts.get("timeout", 0) + counts.get("late", 0)
                     + counts.get("deadline", 0)) / n, 3),
                "error_rate": round(
                    (counts.get("error", 0)
                     + counts.get("server_error", 0)) / n, 3),
                "slo_s": slo_s,
                "max_queued": max_queued if shed_on else -1,
            }
            records.append(rec)
            print(json.dumps(rec), flush=True)
            _drain(base)
    serve.delete("overload_bench")
    if own_cluster:
        try:
            serve.shutdown()
        except Exception:  # noqa: BLE001
            pass
        ray_tpu.shutdown()
    return records


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="tiny shapes for the tier-1 smoke")
    ap.add_argument("--out", default=None,
                    help="write collected records as JSON")
    args = ap.parse_args()
    records = run_suite(quick=args.quick)
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"suite": "bench_serve",
                       "quick": args.quick,
                       "records": records}, f, indent=2)
        print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
