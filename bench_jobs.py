"""Job-plane bench: a Tune-style trial fleet vs the autoscaler, A/B'ing
demand-driven against liveness-reactive scale-up at 500+ simnodes.

The workload (ROADMAP item 5's proof): hundreds of short trial jobs
across 3 tenants — one tenant submitting 10x — burst-submitted into the
durable job table, admitted in weighted fair-share order (the EXACT
`FairShareQueue` the JobManager runs), each trial occupying one
autoscaler-launched simnode for `--trial-s` seconds. The driver plays
the job plane; the REAL reconciler (standalone mode, its own RPC loop
against the store) plays capacity:

  demand:   the reconciler sees queued-job resource shapes straight from
            the submitted-job table (`pending_job_resources`) plus pushed
            `report_demand` entries — capacity provisions before any
            lease exists, so the whole fleet storms up in one pass.
  reactive: the pre-PR signal path — only lease shapes already pending
            on live daemons' heartbeats (capped per node), so capacity
            compounds one poll round at a time from `min_workers`.

Phases per mode:
  trial_fleet        burst submit -> admission -> completion. Reports
                     time-to-first-trial, makespan, ramp-to-90%-capacity,
                     store CPU, per-tenant completed counts, and the
                     fair-share error over the all-tenants-backlogged
                     admission prefix (|share - 1/3| must stay bounded).
  nodes_over_time    sampled {t, alive, running, queued, done} curve.
  scale_down_drain   queue empty -> idle-past-timeout nodes drained and
                     terminated by the reconciler; convergence time to
                     min_workers + store CPU while shrinking.

Plus (--autoscale) the bench_scale.py storm/drain column riding in this
artifact: pure report_demand scale-up of N nodes and drain back to zero.

Zero `protocol_errors` across every simnode is the correctness gate.

Run: python bench_jobs.py [--quick] [--nodes N] [--jobs J]
                          [--out BENCH_JOBS_r16.json]
"""

import argparse
import asyncio
import json
import os
import time


def _proc_cpu_s(pid: int) -> float:
    with open(f"/proc/{pid}/stat") as f:
        parts = f.read().split()
    hz = os.sysconf("SC_CLK_TCK")
    return (int(parts[13]) + int(parts[14])) / hz


def _fair_share_error(admit_log, tenants):
    """Max |admitted share - equal share| at the end of the prefix during
    which EVERY tenant still had backlog (the window where fair share is
    defined), skipping the first few admissions of warmup."""
    counts = {t: 0 for t in tenants}
    err, n = 0.0, 0
    for _ts, tenant, backlog_before in admit_log:
        if any(backlog_before[t] <= 0 for t in tenants):
            break
        counts[tenant] += 1
        n += 1
        if n >= 3 * len(tenants):
            err = max(abs(counts[t] / n - 1.0 / len(tenants))
                      for t in tenants)
    return round(err, 4), n


async def run_mode(mode: str, args) -> list:
    from ray_tpu._private import node as node_mod
    from ray_tpu._private.config import GLOBAL_CONFIG
    from ray_tpu._private.protocol import ResourceSet
    from ray_tpu.autoscaler import Autoscaler, AutoscalingConfig
    from ray_tpu.autoscaler.fake_provider import FakeNodeProvider
    from ray_tpu.job_submission import FairShareQueue
    from ray_tpu.runtime.rpc import RpcClient

    GLOBAL_CONFIG.reset()
    GLOBAL_CONFIG.apply_system_config({
        "node_table_delta_sync": True,
        "pubsub_flush_window_ms": 25.0,
        "heartbeat_jitter": 0.2,
        "control_store_persist": True,
        "autoscaler_job_shapes_max": 1024,
    })
    session = node_mod.new_session_dir()
    cs_proc, addr = node_mod.start_control_store(session)
    provider = FakeNodeProvider(addr, seed=args.seed)
    scaler = Autoscaler(provider, AutoscalingConfig(
        min_workers=1, max_workers=args.nodes,
        worker_resources={"CPU": 4.0},
        idle_timeout_s=args.idle_timeout_s,
        poll_period_s=args.poll_s,
        demand_driven=(mode == "demand"),
    ), control_address=addr).start()

    client = RpcClient(addr, name="bench-jobs")
    await client.connect()

    unit = max(1, args.jobs // 12)
    fleet = [("flood", 10 * unit), ("team-a", unit), ("team-b", unit)]
    tenants = [t for t, _ in fleet]
    total_jobs = sum(n for _, n in fleet)
    trial_res = {"CPU": 4.0}  # one trial fills one worker node
    trial_set = ResourceSet(trial_res)

    results = []

    def rec(phase: str, **fields):
        row = {"bench": phase, "mode": mode, "nodes_max": args.nodes,
               "jobs": total_jobs, **fields}
        results.append(row)
        print(json.dumps(row), flush=True)

    try:
        # -- burst submit into the durable table -------------------------
        queue = FairShareQueue(lambda t: 1.0)
        sid_tenant = {}
        t_sub = time.monotonic()
        puts = []
        for tenant, n in fleet:
            for i in range(n):
                sid = f"trial-{tenant}-{i:04d}"
                sid_tenant[sid] = tenant
                queue.push(tenant, sid, 4.0)
                puts.append(client.call("job_put", {"job": {
                    "submission_id": sid, "tenant": tenant,
                    "entrypoint": f"trial {tenant}/{i}",
                    "status": "QUEUED", "resources": dict(trial_res),
                    "submit_time": time.time()}}, timeout=60))
        await asyncio.gather(*puts)
        submit_s = time.monotonic() - t_sub

        # -- the fleet loop ----------------------------------------------
        running = {}            # sid -> (handle, finish_ts)
        backlog = {t: n for t, n in fleet}
        completed = {t: 0 for t in tenants}
        admit_log, samples = [], []
        done, first_admit, last_sample = 0, None, -1e9
        shape_cap = GLOBAL_CONFIG.get("heartbeat_pending_shapes_max")
        cpu0 = _proc_cpu_s(cs_proc.pid)
        t0 = time.monotonic()
        while done < total_jobs and time.monotonic() - t0 < args.timeout_s:
            now = time.monotonic()
            updates = []
            for sid in [s for s, (_h, fin) in running.items() if fin <= now]:
                h, _fin = running.pop(sid)
                h["sim"].available = h["sim"].available + trial_set
                completed[sid_tenant[sid]] += 1
                done += 1
                updates.append(client.call("job_update", {
                    "submission_id": sid,
                    "fields": {"status": "SUCCEEDED",
                               "end_time": time.time()}}, timeout=60))
            free = [h for h in provider.nodes.values()
                    if h["sim"].state == "ALIVE"
                    and trial_set.is_subset_of(h["sim"].available)]
            while free:
                picked = queue.pop(lambda t, s: True)
                if picked is None:
                    break
                tenant, sid = picked
                h = free.pop()
                h["sim"].available = h["sim"].available - trial_set
                admit_log.append((now, tenant, dict(backlog)))
                backlog[tenant] -= 1
                running[sid] = (h, now + args.trial_s)
                if first_admit is None:
                    first_admit = now
                updates.append(client.call("job_update", {
                    "submission_id": sid,
                    "fields": {"status": "RUNNING",
                               "start_time": time.time()}}, timeout=60))
            if updates:
                await asyncio.gather(*updates)
            # the daemon-visible (reactive) signal: supervisors admitted
            # ahead of capacity pend leases on live daemons — a 2x
            # overcommit window spread node by node, heartbeat-capped;
            # this is ALL the reactive reconciler ever sees
            alive = [h for h in provider.nodes.values()
                     if h["sim"].state == "ALIVE"]
            overflow = min(queue.backlog(),
                           max(0, max(8, 2 * len(alive)) - len(running)))
            for h in alive:
                share = min(overflow, shape_cap)
                h["sim"].pending_shapes = [dict(trial_res)] * share
                overflow -= share
            if now - last_sample >= args.sample_s:
                last_sample = now
                samples.append({
                    "t": round(now - t0, 2), "alive": len(alive),
                    "running": len(running), "done": done,
                    "queued": queue.backlog()})
            await asyncio.sleep(args.tick_s)

        makespan = time.monotonic() - t0
        cpu1 = _proc_cpu_s(cs_proc.pid)
        peak = max((s["alive"] for s in samples), default=0)
        ramp90 = next((s["t"] for s in samples
                       if s["alive"] >= 0.9 * peak), None)
        fs_err, fs_window = _fair_share_error(admit_log, tenants)
        errors = provider.protocol_errors()
        rec("trial_fleet",
            submit_s=round(submit_s, 3),
            time_to_first_trial_s=(
                round(first_admit - t0, 3) if first_admit else None),
            makespan_s=round(makespan, 3),
            ramp_90pct_s=ramp90, peak_nodes=peak,
            store_cpu_frac=round((cpu1 - cpu0) / max(makespan, 1e-9), 4),
            fair_share_err=fs_err, fair_share_window=fs_window,
            completed=completed, timed_out=done < total_jobs,
            protocol_errors=len(errors), errors_sample=errors[:3])
        rec("nodes_over_time", samples=samples)

        # -- scale-down drain --------------------------------------------
        for h in provider.nodes.values():
            h["sim"].pending_shapes = []
        cpu0 = _proc_cpu_s(cs_proc.pid)
        t0 = time.monotonic()
        floor = 1  # min_workers
        while time.monotonic() - t0 < args.drain_timeout_s:
            alive_n = sum(1 for h in provider.nodes.values()
                          if h["sim"].state == "ALIVE")
            if alive_n <= floor:
                break
            await asyncio.sleep(0.25)
        drain_s = time.monotonic() - t0
        cpu1 = _proc_cpu_s(cs_proc.pid)
        errors = provider.protocol_errors()
        rec("scale_down_drain",
            drain_s=round(drain_s, 3),
            final_nodes=sum(1 for h in provider.nodes.values()
                            if h["sim"].state == "ALIVE"),
            converged=drain_s < args.drain_timeout_s,
            store_cpu_frac=round((cpu1 - cpu0) / max(drain_s, 1e-9), 4),
            protocol_errors=len(errors))
    finally:
        await client.close()
        scaler.stop()
        provider.shutdown()
        node_mod.kill_process(cs_proc, force=True)
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=0,
                    help="max autoscaled simnodes (default 520, or 10 with "
                         "--quick)")
    ap.add_argument("--jobs", type=int, default=0,
                    help="total trial jobs across the 3 tenants (default "
                         "600, or 24 with --quick)")
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--mode", choices=["demand", "reactive", "both"],
                    default="both")
    ap.add_argument("--seed", type=int, default=115)
    ap.add_argument("--trial-s", type=float, default=0.0,
                    help="per-trial runtime (default 2.0, or 0.6 quick)")
    ap.add_argument("--poll-s", type=float, default=0.0,
                    help="autoscaler poll period (default 0.5, 0.3 quick)")
    ap.add_argument("--idle-timeout-s", type=float, default=0.0,
                    help="autoscaler idle timeout (default 6.0, 1.5 quick)")
    ap.add_argument("--tick-s", type=float, default=0.1)
    ap.add_argument("--sample-s", type=float, default=0.0)
    ap.add_argument("--timeout-s", type=float, default=600.0)
    ap.add_argument("--drain-timeout-s", type=float, default=180.0)
    ap.add_argument("--autoscale", action="store_true",
                    help="also run bench_scale's pure storm/drain column "
                         "into this artifact")
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    args.nodes = args.nodes or (10 if args.quick else 520)
    args.jobs = args.jobs or (24 if args.quick else 600)
    args.trial_s = args.trial_s or (0.6 if args.quick else 2.0)
    args.poll_s = args.poll_s or (0.3 if args.quick else 0.5)
    args.idle_timeout_s = args.idle_timeout_s or (1.5 if args.quick else 6.0)
    args.sample_s = args.sample_s or (0.5 if args.quick else 1.0)

    modes = (["demand", "reactive"] if args.mode == "both" else [args.mode])
    all_results = []
    for mode in modes:
        all_results.extend(asyncio.run(run_mode(mode, args)))
    if args.autoscale:
        import bench_scale

        sc_args = argparse.Namespace(nodes=min(args.nodes, 500),
                                     seed=args.seed)
        all_results.extend(asyncio.run(bench_scale.run_autoscale(sc_args)))
    if args.out:
        with open(args.out, "w") as f:
            json.dump({
                "bench": "bench_jobs",
                "ts": time.strftime("%Y-%m-%d %H:%M:%S"),
                "nodes": args.nodes, "jobs": args.jobs,
                "trial_s": args.trial_s, "seed": args.seed,
                "results": all_results,
            }, f, indent=1)
        print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
