"""Numerics check: Pallas flash fwd+bwd vs XLA attention on TPU."""
import jax, jax.numpy as jnp
import numpy as np
from ray_tpu.ops.flash_attention import (
    flash_attention_bhsd, _xla_attention_bhsd)

b, h, kvh, s, hd = 2, 4, 2, 1024, 128
key = jax.random.key(0)
kq, kk, kv, kg = jax.random.split(key, 4)
q = jax.random.normal(kq, (b, h, s, hd), jnp.bfloat16)
k = jax.random.normal(kk, (b, kvh, s, hd), jnp.bfloat16)
v = jax.random.normal(kv, (b, kvh, s, hd), jnp.bfloat16)
g = jax.random.normal(kg, (b, h, s, hd), jnp.bfloat16)

for causal in (True, False):
    for bq, bk in ((512, 512), (256, 512), (512, 1024)):
        def f_flash(q, k, v):
            return flash_attention_bhsd(q, k, v, causal=causal,
                                        block_q=bq, block_k=bk)
        def f_xla(q, k, v):
            return _xla_attention_bhsd(q, k, v, causal)

        o1, vjp1 = jax.vjp(f_flash, q, k, v)
        o2, vjp2 = jax.vjp(f_xla, q, k, v)
        g1 = vjp1(g); g2 = vjp2(g)
        eo = float(jnp.max(jnp.abs(o1.astype(jnp.float32) - o2.astype(jnp.float32))))
        errs = [float(jnp.max(jnp.abs(a.astype(jnp.float32) - b_.astype(jnp.float32))))
                for a, b_ in zip(g1, g2)]
        print(f"causal={causal} bq={bq} bk={bk} o_err={eo:.4f} "
              f"dq={errs[0]:.4f} dk={errs[1]:.4f} dv={errs[2]:.4f}")
        assert eo < 0.1 and all(e < 0.25 for e in errs), "MISMATCH"
print("OK")
