"""Spot-preemption survival A/B: proactive notice plane vs reactive drain.

The experiment (r18 tentpole): a correlated reclaim wave hits 30% of a
spot fleet. Two worlds, one lever (`AutoscalingConfig.preempt_proactive`):

  reactive   the legacy path — a victim's watcher turns the notice into an
             immediate terminal self-drain. The node stops serving AT the
             notice, its capacity is gone for the whole reclaim window,
             and the autoscaler only launches a replacement once the
             workload's re-pended demand surfaces after the death.
  proactive  the notice plane — victims publish a TTL'd
             report_preemption_notice and sit in the reversible PREEMPTING
             state, still serving committed work. The autoscaler treats
             their committed load as demand NOW, launches replacements in
             the same tranche machinery, and starts each victim's drain
             only once its replacement has REGISTERED — overlapping
             replacement boot with the reclaim window instead of
             serializing them.

Phase 1 — capacity wave (simnode-backed, both modes): a spot SimNode fleet
plus the REAL autoscaler reconciler over FakeNodeProvider. A seeded wave
preempts 30%; a monitor samples the store's node table and stamps:
  first_loss_ts      first victim leaves serving capacity (DRAINING/DEAD)
  replacement_ts     first autoscaler-launched node ALIVE at the store
  restored_ts        ALIVE serving capacity back at the baseline width
  downtime_s         max(0, restored_ts - first_loss_ts): the train
                     downtime-per-wave proxy — how long an elastic gang
                     would run below target width
Gates: proactive must have the replacement registered BEFORE the first
victim exits, strictly lower downtime than reactive, and ZERO simnode
protocol errors in both modes.

Phase 2 — serve goodput wave (real subprocess cluster, both modes, skipped
with --quick): a 2-replica deployment spread across two spot hosts under
open-loop traffic; the wave reclaims one replica's host via the runtime
chaos_set fault. Counters (ok / failed / lost-object errors) bound the
goodput dip and prove recovery — the r12 overload-harness discipline
(counter-asserted, never eyeballed).

Emits one JSON record per (phase, mode) on stdout; --out writes the
collected artifact (BENCH_PREEMPT_rNN.json).

Run: python bench_preempt.py [--quick] [--spots N] [--out BENCH_PREEMPT_r18.json]
"""

import argparse
import asyncio
import json
import threading
import time

WAVE_FRAC = 0.3


def _mode_config(mode: str) -> dict:
    return {
        "node_table_delta_sync": True,
        "pubsub_flush_window_ms": 5.0,
        "heartbeat_period_s": 0.25,
        "preempt_proactive": mode == "proactive",
        "preempt_republish_period_s": 0.2,
        "preempt_notice_ttl_s": 30.0,
    }


async def run_capacity_wave(mode: str, *, spots: int, deadline_s: float,
                            seed: int) -> dict:
    """One wave against one fleet; returns the metrics record. In-process
    control store + simnode fleet + the real autoscaler reconciler."""
    from ray_tpu._private.config import GLOBAL_CONFIG
    from ray_tpu._private.control_store import ControlStore
    from ray_tpu._private.simnode import SimNodePlane
    from ray_tpu.autoscaler import Autoscaler, AutoscalingConfig
    from ray_tpu.autoscaler.fake_provider import FakeNodeProvider

    GLOBAL_CONFIG.reset()
    GLOBAL_CONFIG.apply_system_config(_mode_config(mode))
    bin_res = {"CPU": 4.0}

    cs = ControlStore()
    addr = await cs.start(port=0)
    plane = SimNodePlane(addr, spots, seed=seed, resources=dict(bin_res),
                         spot_fraction=1.0)
    await plane.start()
    await plane.await_converged(timeout=60)
    baseline_ids = {n.node_id.hex() for n in plane.alive()}
    baseline = len(baseline_ids)

    # a fleet sized to its workload: every bin fully committed (the
    # victims' committed load cannot migrate into survivor headroom, so a
    # replacement NODE is the only way out — the scenario the notice
    # plane exists for). Wait a beat so the store's availability view
    # reflects it before the wave.
    from ray_tpu._private.protocol import ResourceSet

    for n in plane.alive():
        n.available = ResourceSet({})
    await asyncio.sleep(
        2.5 * GLOBAL_CONFIG.get("heartbeat_period_s"))

    provider = FakeNodeProvider(addr, seed=seed)
    scaler = Autoscaler(provider, AutoscalingConfig(
        min_workers=0, max_workers=spots * 2,
        worker_resources=dict(bin_res),
        idle_timeout_s=120.0, poll_period_s=0.2,
        demand_driven=True,
        preempt_proactive=(mode == "proactive"),
    ), control_address=addr).start()

    stamps = {"first_loss": None, "replacement": None, "restored": None}
    stop = asyncio.Event()
    requeued = {"done": False}

    async def monitor():
        """Sample the store's node table; stamp the capacity timeline. In
        reactive mode, also play the workload's part: when a victim DIES,
        its tasks re-pend on a survivor (the demand signal reactive mode
        has to wait for)."""
        while not stop.is_set():
            rows = {n["node_id"].hex(): n["state"]
                    for n in (await cs.rpc_get_all_nodes(0, {}))["nodes"]}
            now = time.monotonic()
            alive = {h for h, s in rows.items() if s == "ALIVE"}
            lost = {h for h in baseline_ids
                    if rows.get(h) in ("DRAINING", "DEAD")}
            dead = {h for h in baseline_ids if rows.get(h) == "DEAD"}
            if lost and stamps["first_loss"] is None:
                stamps["first_loss"] = now
            if (alive - baseline_ids) and stamps["replacement"] is None:
                stamps["replacement"] = now
            if (mode == "reactive" and dead and not requeued["done"]
                    and plane.alive()):
                requeued["done"] = True
                survivor = plane.alive()[0]
                survivor.pending_shapes = [dict(bin_res)] * len(
                    baseline_ids - alive)
            if (stamps["first_loss"] is not None
                    and len(alive) >= baseline
                    and stamps["restored"] is None):
                stamps["restored"] = now
                if requeued["done"] and plane.alive():
                    plane.alive()[0].pending_shapes = []
            await asyncio.sleep(0.03)

    mon = asyncio.ensure_future(monitor())
    t_wave0 = time.monotonic()
    wave = await plane.preempt_wave(
        WAVE_FRAC, window_s=0.2, deadline_s=deadline_s,
        proactive=(mode == "proactive"), rng_seed=seed)

    # ride out the tail: replacements must register and capacity restore
    tail_deadline = time.monotonic() + 30.0
    while time.monotonic() < tail_deadline and stamps["restored"] is None:
        await asyncio.sleep(0.05)
    stop.set()
    await mon

    first_exit = min((n.gone_ts for n in plane.nodes
                      if n.index in set(wave["victims"])
                      and n.gone_ts is not None), default=None)
    errors = (plane.stats()["protocol_errors"]
              + [e for h in provider.nodes.values()
                 for e in h["sim"].protocol_errors])
    rel = lambda ts: round(ts - t_wave0, 3) if ts is not None else None  # noqa: E731
    downtime = (max(0.0, stamps["restored"] - stamps["first_loss"])
                if stamps["restored"] and stamps["first_loss"] else None)
    record = {
        "bench": "preempt_capacity_wave", "mode": mode,
        "spot_fleet": wave["spot_fleet"], "wave_frac": WAVE_FRAC,
        "victims": len(wave["victims"]), "deadline_s": deadline_s,
        "graceful_exits": wave["graceful"], "deadline_kills": wave["killed"],
        "first_notice_s": rel(wave["first_notice"]),
        "first_loss_s": rel(stamps["first_loss"]),
        "replacement_registered_s": rel(stamps["replacement"]),
        "capacity_restored_s": rel(stamps["restored"]),
        "train_downtime_per_wave_s": round(downtime, 3)
        if downtime is not None else None,
        "replacement_before_first_exit": bool(
            stamps["replacement"] is not None and first_exit is not None
            and stamps["replacement"] < first_exit),
        "preempt_stats": dict(scaler.preempt_stats),
        "protocol_errors": len(errors), "errors_sample": errors[:3],
        "unit": "s",
    }

    # stop() blocks on control RPCs; run it off-loop so the in-process
    # store (served by THIS loop) can still answer them — calling it
    # inline deadlocks the reconcile thread into its join timeout
    await asyncio.to_thread(scaler.stop)
    await asyncio.to_thread(provider.shutdown)
    await plane.stop()
    await cs.stop()
    return record


def run_serve_wave(mode: str, *, seed: int) -> dict:
    """Phase 2: the serve goodput dip under a real-cluster wave."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu._private.config import GLOBAL_CONFIG
    from ray_tpu._private.core_worker import get_core_worker
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.runtime.rpc import RpcClient

    GLOBAL_CONFIG.reset()
    cfg = _mode_config(mode)
    cfg.update({
        "testing_chaos_seed": seed,
        "health_check_period_s": 0.25,
        "health_check_timeout_s": 2.0,
        "serve_replica_init_timeout_s": 10.0,
    })
    GLOBAL_CONFIG.apply_system_config(cfg)
    cluster = Cluster(initialize_head=True, head_resources={"CPU": 4})
    try:
        spots = [cluster.add_node(resources={"CPU": 2, "spot": 1},
                                  labels={"spot": "true"}),
                 cluster.add_node(resources={"CPU": 2, "spot": 1},
                                  labels={"spot": "true"})]
        ray_tpu.init(address=cluster.address)
        cw = get_core_worker()

        @serve.deployment(num_replicas=2, name="PreemptEcho",
                          ray_actor_options={"resources": {"spot": 1}})
        class PreemptEcho:
            def __call__(self, x):
                return x * 2

        handle = serve.run(PreemptEcho.bind())
        assert handle.remote(1).result(timeout=60) == 2

        counts = {"ok": 0, "failed": 0, "lost_objects": 0}
        stop = threading.Event()

        def traffic():
            i = 0
            while not stop.is_set():
                try:
                    assert handle.options(
                        timeout_s=5.0).remote(i).result(timeout=30) == i * 2
                    counts["ok"] += 1
                except Exception as e:  # noqa: BLE001 — classified below
                    counts["failed"] += 1
                    if "ObjectLost" in type(e).__name__:
                        counts["lost_objects"] += 1
                i += 1
                time.sleep(0.05)

        t = threading.Thread(target=traffic)
        t.start()
        dip_recovered = False
        post_recovery_failures = None
        try:
            time.sleep(1.0)
            pre_ok = counts["ok"]

            actors = cw.run_sync(
                cw.control.call("list_actors", {}), 30)["actors"]
            replica_nodes = {a["node_id"].hex() for a in actors
                             if (a.get("name") or "").startswith(
                                 "serve:PreemptEcho:") and a["node_id"]}
            victim = next((s for s in spots
                           if s.node_id in replica_nodes), spots[0])

            async def aim():
                c = RpcClient(victim.address, name="bench-wave")
                try:
                    return await c.call("chaos_set", {"config": {
                        "testing_preempt_wave": "1.0:100:8000",
                        "testing_chaos_seed": seed}}, timeout=15)
                finally:
                    await c.close()

            assert cw.run_sync(aim(), timeout=30)["ok"]
            t_wave = time.monotonic()

            # wait for the victim's death, then for goodput to resume
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline:
                rows = cw.run_sync(
                    cw.control.call("get_all_nodes", {}), 15)["nodes"]
                st = next((n["state"] for n in rows
                           if n["node_id"].hex() == victim.node_id), None)
                if st == "DEAD":
                    break
                time.sleep(0.25)
            target = counts["ok"] + 20
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and counts["ok"] < target:
                time.sleep(0.2)
            dip_recovered = counts["ok"] >= target
            failed_at_recovery = counts["failed"]
            time.sleep(3.0)
            post_recovery_failures = counts["failed"] - failed_at_recovery
            wave_s = round(time.monotonic() - t_wave, 3)
        finally:
            stop.set()
            t.join(timeout=30)

        total = counts["ok"] + counts["failed"]
        return {
            "bench": "preempt_serve_wave", "mode": mode,
            "pre_wave_ok": pre_ok, "ok": counts["ok"],
            "failed": counts["failed"],
            "lost_objects": counts["lost_objects"],
            "dip_recovered": dip_recovered,
            "post_recovery_failures": post_recovery_failures,
            "dip_bounded": bool(
                dip_recovered and post_recovery_failures is not None
                and post_recovery_failures <= 5
                and counts["failed"] <= max(10, total * 0.5)),
            "wave_to_recovery_s": wave_s,
            "unit": "req",
        }
    finally:
        try:
            ray_tpu.shutdown()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass
        cluster.shutdown()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="small fleet, short deadlines, skip the serve leg")
    ap.add_argument("--spots", type=int, default=None,
                    help="spot fleet size (default 10, or 6 with --quick)")
    ap.add_argument("--seed", type=int, default=18)
    ap.add_argument("--out", default="")
    args = ap.parse_args()

    spots = args.spots or (6 if args.quick else 10)
    deadline_s = 2.5 if args.quick else 6.0
    results = []
    for mode in ("reactive", "proactive"):
        rec = asyncio.run(run_capacity_wave(
            mode, spots=spots, deadline_s=deadline_s, seed=args.seed))
        results.append(rec)
        print(json.dumps(rec), flush=True)
    if not args.quick:
        for mode in ("reactive", "proactive"):
            rec = run_serve_wave(mode, seed=args.seed)
            results.append(rec)
            print(json.dumps(rec), flush=True)

    by_mode = {r["mode"]: r for r in results
               if r["bench"] == "preempt_capacity_wave"}
    summary = {
        "bench": "preempt_summary",
        "wave_frac": WAVE_FRAC,
        "proactive_replacement_before_first_exit":
            by_mode["proactive"]["replacement_before_first_exit"],
        "train_downtime_per_wave_s": {
            m: by_mode[m]["train_downtime_per_wave_s"] for m in by_mode},
        "proactive_strictly_lower_downtime": bool(
            by_mode["proactive"]["train_downtime_per_wave_s"] is not None
            and by_mode["reactive"]["train_downtime_per_wave_s"] is not None
            and by_mode["proactive"]["train_downtime_per_wave_s"]
            < by_mode["reactive"]["train_downtime_per_wave_s"]),
        "protocol_errors": {
            m: by_mode[m]["protocol_errors"] for m in by_mode},
    }
    results.append(summary)
    print(json.dumps(summary), flush=True)

    if args.out:
        with open(args.out, "w") as f:
            json.dump(results, f, indent=1)
        print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
