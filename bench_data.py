"""Data-plane benchmarks: pipeline throughput + driver RSS, with the
logical optimizer's fusion/pushdown A/B'd via the
`DataContext.optimizer_enabled` escape hatch.

Counterpart of the reference's data release benchmarks
(release/nightly_tests/dataset/). Emits one JSON line per benchmark:
{"bench": ..., "optimizer": "on"|"off", "value": ..., "unit": ...} and
writes the collected artifact (BENCH_DATA_rNN.json) with --out.

Run: python bench_data.py [--quick] [--out BENCH_DATA_r09.json]
"""

import argparse
import json
import resource
import time


def _rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def _with_optimizer(enabled: bool):
    from ray_tpu.data.context import DataContext

    ctx = DataContext.get_current()
    ctx.optimizer_enabled = enabled


def bench_fused_pipeline(rd, n_rows: int, n_blocks: int, enabled: bool):
    """A 4-op vectorized map_batches chain over MANY small blocks,
    streamed end to end. Fusion (optimizer on) runs ONE task per block;
    off runs one task per op per block (4x the dispatches + 3 extra block
    round-trips through the store) — the A/B isolates the task-hop
    overhead fusion removes."""
    _with_optimizer(enabled)

    def make():
        ds = rd.range(n_rows, parallelism=n_blocks)
        for _ in range(4):
            ds = ds.map_batches(lambda b: {"id": b["id"] + 1})
        return ds

    def consume(ds):
        from ray_tpu.data.block import block_num_rows

        return sum(block_num_rows(b) for b in ds.iter_blocks())

    consume(make())  # warmup: worker pool + imports
    t0 = time.perf_counter()
    rows = consume(make())
    dt = time.perf_counter() - t0
    return {"bench": "fused_pipeline",
            "optimizer": "on" if enabled else "off",
            "value": round(n_rows / dt, 1), "unit": "rows/s",
            "rows_out": rows}


def bench_limit_pushdown(rd, n_rows: int, n_blocks: int, k: int,
                         enabled: bool):
    """range.map(expensive).limit(k): the LimitPushdown rule moves the
    per-block cap below the map, so the map touches <= k-ish rows; with
    the optimizer off it processes every admitted block in full."""
    import numpy as np

    _with_optimizer(enabled)

    def expensive(r):
        x = float(r["id"])
        for _ in range(50):
            x = np.sqrt(x * x + 1.0)
        return {"id": r["id"], "x": x}

    def make():
        return rd.range(n_rows, parallelism=n_blocks).map(expensive).limit(k)

    make().take(8)  # warmup
    t0 = time.perf_counter()
    rows = make().take_all()
    dt = time.perf_counter() - t0
    assert len(rows) == k
    return {"bench": "limit_pushdown",
            "optimizer": "on" if enabled else "off",
            "value": round(dt * 1e3, 1), "unit": "ms"}


def _write_parquet_dir(n_files: int, rows: int, n_cols: int) -> str:
    import tempfile

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    d = tempfile.mkdtemp(prefix="rt_bench_data_")
    for i in range(n_files):
        cols = {"key": np.arange(i * rows, (i + 1) * rows)}
        for c in range(n_cols):
            cols[f"pad{c}"] = np.random.default_rng(c).random(rows)
        pq.write_table(pa.table(cols), f"{d}/part{i}.parquet")
    return d


def bench_parquet_projection(rd, path: str, total_rows: int, enabled: bool):
    """sum("key") over a wide parquet set: projection pushdown reads ONE
    column; off reads every pad column then drops them."""
    _with_optimizer(enabled)
    rd.read_parquet(path).sum("key")  # warmup (fresh dataset: no ref reuse)
    t0 = time.perf_counter()
    total = rd.read_parquet(path).sum("key")
    dt = time.perf_counter() - t0
    assert total == sum(range(total_rows))
    return {"bench": "parquet_projection_sum",
            "optimizer": "on" if enabled else "off",
            "value": round(dt * 1e3, 1), "unit": "ms"}


def bench_parquet_count_metadata(rd, path: str, total_rows: int,
                                 enabled: bool):
    """count() on a fresh read_parquet: on = footer arithmetic (zero data
    blocks), off = execute every read task then count."""
    _with_optimizer(enabled)
    rd.read_parquet(path).count()  # warmup (fresh dataset: no ref reuse)
    t0 = time.perf_counter()
    n = rd.read_parquet(path).count()
    dt = time.perf_counter() - t0
    assert n == total_rows
    return {"bench": "parquet_count",
            "optimizer": "on" if enabled else "off",
            "value": round(dt * 1e3, 1), "unit": "ms"}


def run_suite(quick: bool = False):
    """Assumes ray_tpu.init() already ran. Returns the result list."""
    import ray_tpu.data as rd

    if quick:
        n_rows, n_blocks, k = 4_000, 4, 50
        pq_files, pq_rows, pq_cols = 2, 500, 4
    else:
        n_rows, n_blocks, k = 2_000_000, 256, 1_000
        pq_files, pq_rows, pq_cols = 16, 100_000, 16
    pq_dir = _write_parquet_dir(pq_files, pq_rows, pq_cols)
    total_pq = pq_files * pq_rows

    rss0 = _rss_mb()
    results = []
    try:
        for enabled in (True, False):
            results.append(
                bench_fused_pipeline(rd, n_rows, n_blocks, enabled))
            results.append(
                bench_limit_pushdown(rd, n_rows, n_blocks, k, enabled))
            results.append(
                bench_parquet_projection(rd, pq_dir, total_pq, enabled))
            results.append(
                bench_parquet_count_metadata(rd, pq_dir, total_pq, enabled))
    finally:
        _with_optimizer(True)
    results.append({"bench": "driver_rss_delta", "optimizer": "n/a",
                    "value": round(_rss_mb() - rss0, 1), "unit": "MB"})
    return results


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--out", default=None,
                        help="write the artifact JSON here")
    args = parser.parse_args()

    import ray_tpu

    ray_tpu.init(num_cpus=4)
    try:
        results = run_suite(quick=args.quick)
    finally:
        ray_tpu.shutdown()
    for r in results:
        print(json.dumps(r))
    if args.out:
        import platform

        artifact = {
            "suite": "bench_data",
            "quick": bool(args.quick),
            "python": platform.python_version(),
            "machine": platform.machine(),
            "results": results,
        }
        with open(args.out, "w") as f:
            json.dump(artifact, f, indent=2)
            f.write("\n")


if __name__ == "__main__":
    main()
